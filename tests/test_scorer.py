from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import PodEntry
from llm_d_kv_cache_manager_tpu.kvcache.scorer import (
    ScorerConfig,
    new_scorer,
)


def entry(pod, tier="hbm"):
    return PodEntry(pod, tier)


def make_scorer():
    return new_scorer(ScorerConfig())


class TestLongestPrefixScorer:
    def test_empty_keys(self):
        assert make_scorer().score([], {}) == {}

    def test_single_pod_full_prefix(self):
        scorer = make_scorer()
        keys = [1, 2, 3]
        mapping = {k: [entry("a")] for k in keys}
        assert scorer.score(keys, mapping) == {"a": 3.0}

    def test_prefix_break_stops_scoring(self):
        scorer = make_scorer()
        keys = [1, 2, 3]
        mapping = {1: [entry("a")], 3: [entry("a")]}  # gap at key 2
        assert scorer.score(keys, mapping) == {"a": 1.0}

    def test_pod_missing_from_first_key_scores_zero(self):
        scorer = make_scorer()
        keys = [1, 2]
        mapping = {1: [entry("a")], 2: [entry("a"), entry("b")]}
        scores = scorer.score(keys, mapping)
        assert scores == {"a": 2.0}
        assert "b" not in scores

    def test_intersection_shrinks_active_set(self):
        scorer = make_scorer()
        keys = [1, 2, 3]
        mapping = {
            1: [entry("a"), entry("b")],
            2: [entry("a"), entry("b")],
            3: [entry("a")],
        }
        assert scorer.score(keys, mapping) == {"a": 3.0, "b": 2.0}

    def test_tier_weights(self):
        scorer = make_scorer()
        keys = [1, 2]
        mapping = {
            1: [entry("a", "host"), entry("b", "hbm")],
            2: [entry("a", "host"), entry("b", "shared_storage")],
        }
        scores = scorer.score(keys, mapping)
        assert scores["a"] == 1.6  # 0.8 + 0.8
        assert scores["b"] == 1.5  # 1.0 + 0.5

    def test_max_weight_across_tiers_same_pod(self):
        scorer = make_scorer()
        mapping = {1: [entry("a", "host"), entry("a", "hbm")]}
        assert scorer.score([1], mapping) == {"a": 1.0}

    def test_unknown_tier_defaults_to_one(self):
        scorer = make_scorer()
        mapping = {1: [entry("a", "mystery-tier")]}
        assert scorer.score([1], mapping) == {"a": 1.0}

    def test_unknown_tier_logs_once_per_tier(self):
        """Demotion events introduce new tier strings to deployments
        whose weight table predates them: the fallback must be LOUD
        exactly once per tier name, never per block (the satellite's
        regression pin; docs/configuration.md §5).  The kvtpu root
        logger does not propagate, so the capture handler attaches to
        the scorer's logger directly."""
        import logging

        records = []

        class Capture(logging.Handler):
            def emit(self, record):
                records.append(record.getMessage())

        handler = Capture(level=logging.WARNING)
        target = logging.getLogger("kvtpu.kvcache.scorer")
        target.addHandler(handler)
        try:
            scorer = make_scorer()
            mapping = {
                1: [entry("a", "mystery-tier")],
                2: [entry("a", "mystery-tier")],
                3: [entry("a", "second-mystery")],
            }
            # score() resolves via _resolve; explain() via _best_entry
            # — both route through the warn-once fallback.
            assert scorer.score([1, 2, 3], mapping) == {"a": 3.0}
            scorer.explain([1, 2, 3], mapping)
        finally:
            target.removeHandler(handler)
        warnings = [m for m in records if "unknown device tier" in m]
        assert len(warnings) == 2, warnings
        assert any("mystery-tier" in w for w in warnings)
        assert any("second-mystery" in w for w in warnings)

    def test_gpu_aliases_supported(self):
        scorer = make_scorer()
        mapping = {1: [entry("a", "gpu"), entry("b", "cpu")]}
        assert scorer.score([1], mapping) == {"a": 1.0, "b": 0.8}


class TestExplain:
    """LongestPrefixScorer.explain: the ``explain=1`` provenance surface.

    Invariant: explain's per-pod score always equals score()'s."""

    def test_empty_keys(self):
        assert make_scorer().explain([], {}) == {}

    def test_full_chain_no_break(self):
        scorer = make_scorer()
        keys = [1, 2, 3]
        mapping = {k: [entry("a")] for k in keys}
        detail = scorer.explain(keys, mapping)
        assert detail["a"]["score"] == 3.0
        assert detail["a"]["blocks_matched"] == 3
        assert detail["a"]["break_index"] is None
        assert detail["a"]["tiers"] == {"hbm": 3}

    def test_break_index_names_first_missing_block(self):
        scorer = make_scorer()
        keys = [1, 2, 3, 4]
        mapping = {1: [entry("a")], 2: [entry("a")], 4: [entry("a")]}
        detail = scorer.explain(keys, mapping)
        assert detail["a"]["blocks_matched"] == 2
        assert detail["a"]["break_index"] == 2  # block index 2 missing

    def test_pod_absent_from_block_zero_omitted(self):
        scorer = make_scorer()
        mapping = {1: [entry("a")], 2: [entry("a"), entry("b")]}
        detail = scorer.explain([1, 2], mapping)
        assert "b" not in detail

    def test_tier_attribution_per_block(self):
        scorer = make_scorer()
        keys = [1, 2, 3]
        mapping = {
            1: [entry("a", "hbm")],
            2: [entry("a", "host")],
            3: [entry("a", "host"), entry("a", "hbm")],
        }
        detail = scorer.explain(keys, mapping)
        # Max-weight tier wins per block: hbm, host, hbm.
        assert detail["a"]["tiers"] == {"hbm": 2, "host": 1}
        assert detail["a"]["score"] == 1.0 + 0.8 + 1.0

    def test_divergent_break_points_across_pods(self):
        scorer = make_scorer()
        keys = [1, 2, 3]
        mapping = {
            1: [entry("a"), entry("b")],
            2: [entry("a")],
            3: [entry("a")],
        }
        detail = scorer.explain(keys, mapping)
        assert detail["a"]["break_index"] is None
        assert detail["b"]["break_index"] == 1
        assert detail["b"]["blocks_matched"] == 1

    def test_explain_scores_always_match_score(self):
        import random

        rng = random.Random(7)
        scorer = make_scorer()
        tiers = ["hbm", "host", "shared_storage", "gpu", "cpu"]
        for _ in range(50):
            keys = list(range(rng.randint(0, 12)))
            mapping = {}
            for k in keys:
                if rng.random() < 0.8:
                    mapping[k] = [
                        entry(f"p{rng.randint(0, 3)}", rng.choice(tiers))
                        for _ in range(rng.randint(1, 3))
                    ]
            expected = scorer.score(keys, mapping)
            detail = scorer.explain(keys, mapping)
            assert {
                pod: d["score"] for pod, d in detail.items()
            } == expected
