from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import PodEntry
from llm_d_kv_cache_manager_tpu.kvcache.scorer import (
    ScorerConfig,
    new_scorer,
)


def entry(pod, tier="hbm"):
    return PodEntry(pod, tier)


def make_scorer():
    return new_scorer(ScorerConfig())


class TestLongestPrefixScorer:
    def test_empty_keys(self):
        assert make_scorer().score([], {}) == {}

    def test_single_pod_full_prefix(self):
        scorer = make_scorer()
        keys = [1, 2, 3]
        mapping = {k: [entry("a")] for k in keys}
        assert scorer.score(keys, mapping) == {"a": 3.0}

    def test_prefix_break_stops_scoring(self):
        scorer = make_scorer()
        keys = [1, 2, 3]
        mapping = {1: [entry("a")], 3: [entry("a")]}  # gap at key 2
        assert scorer.score(keys, mapping) == {"a": 1.0}

    def test_pod_missing_from_first_key_scores_zero(self):
        scorer = make_scorer()
        keys = [1, 2]
        mapping = {1: [entry("a")], 2: [entry("a"), entry("b")]}
        scores = scorer.score(keys, mapping)
        assert scores == {"a": 2.0}
        assert "b" not in scores

    def test_intersection_shrinks_active_set(self):
        scorer = make_scorer()
        keys = [1, 2, 3]
        mapping = {
            1: [entry("a"), entry("b")],
            2: [entry("a"), entry("b")],
            3: [entry("a")],
        }
        assert scorer.score(keys, mapping) == {"a": 3.0, "b": 2.0}

    def test_tier_weights(self):
        scorer = make_scorer()
        keys = [1, 2]
        mapping = {
            1: [entry("a", "host"), entry("b", "hbm")],
            2: [entry("a", "host"), entry("b", "shared_storage")],
        }
        scores = scorer.score(keys, mapping)
        assert scores["a"] == 1.6  # 0.8 + 0.8
        assert scores["b"] == 1.5  # 1.0 + 0.5

    def test_max_weight_across_tiers_same_pod(self):
        scorer = make_scorer()
        mapping = {1: [entry("a", "host"), entry("a", "hbm")]}
        assert scorer.score([1], mapping) == {"a": 1.0}

    def test_unknown_tier_defaults_to_one(self):
        scorer = make_scorer()
        mapping = {1: [entry("a", "mystery-tier")]}
        assert scorer.score([1], mapping) == {"a": 1.0}

    def test_gpu_aliases_supported(self):
        scorer = make_scorer()
        mapping = {1: [entry("a", "gpu"), entry("b", "cpu")]}
        assert scorer.score([1], mapping) == {"a": 1.0, "b": 0.8}
