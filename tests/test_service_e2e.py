"""Deep end-to-end suite THROUGH the booted HTTP service.

Counterpart of the reference's e2e suite
(tests/e2e/redis_mock/e2e_test.go:117-921), scenario for scenario:
cache hit/miss, prefix reduction, prefix expansion churn (store -> score
-> store more -> rescore), ~4.5k-token prompts, chat-completions flows
incl. long multi-turn conversations, tokenizer auto-discovery (plain and
HF-cache layouts) *through the booted service*, and eviction-then-
rescore.  The write path is the real event pool (msgpack-encoded
batches, chained engine hashes); the read path is real HTTP against
``api/http_service.py``.  The reference mocks its chat wrapper
(e2e_test.go:76-112); the tiny in-process transformers tokenizer plays
that role here.
"""

from __future__ import annotations

import json
import os
import urllib.request

import pytest

from llm_d_kv_cache_manager_tpu.api.http_service import serve
from llm_d_kv_cache_manager_tpu.kvcache.indexer import Indexer, IndexerConfig
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import (
    IndexConfig,
    InMemoryIndexConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvevents.events import (
    BlockRemoved,
    BlockStored,
    EventBatch,
)
from llm_d_kv_cache_manager_tpu.kvevents.pool import (
    Message,
    Pool,
    PoolConfig,
)
from llm_d_kv_cache_manager_tpu.tokenization.pool import (
    TokenizationPoolConfig,
)
from tests.helpers.tiny_tokenizer import (
    build_fast_tokenizer,
    build_transformers_tokenizer,
    save_tokenizer_json,
)

MODEL = "test-model"
BLOCK_SIZE = 4
SENTENCE = "the quick brown fox jumps over the lazy dog . "  # 10 tokens


class ServiceFleet:
    """The booted stack + helpers shared by every scenario."""

    def __init__(self, indexer, event_pool, base_url):
        self.indexer = indexer
        self.event_pool = event_pool
        self.base_url = base_url
        self._next_hash = 0x1000

    # -- write path (real event pool, chained engine hashes) --

    def publish(self, pod, tokens, parent=None, medium="hbm"):
        """One BlockStored batch for every full block of ``tokens``;
        returns the engine hashes.  ``parent`` chains onto an earlier
        batch's last hash (prefix expansion, reference
        e2e_test.go:178-213)."""
        n_blocks = len(tokens) // BLOCK_SIZE
        hashes = [self._next_hash + i for i in range(n_blocks)]
        self._next_hash += n_blocks
        batch = EventBatch(
            ts=1.0,
            events=[
                BlockStored(
                    block_hashes=hashes,
                    parent_block_hash=parent,
                    token_ids=tokens[: n_blocks * BLOCK_SIZE],
                    block_size=BLOCK_SIZE,
                    medium=medium,
                )
            ],
        )
        self._send(pod, batch)
        return hashes

    def evict(self, pod, hashes):
        self._send(
            pod, EventBatch(ts=2.0, events=[BlockRemoved(block_hashes=hashes)])
        )

    def _send(self, pod, batch):
        self.event_pool.add_task(
            Message(
                topic=f"kv@{pod}@{MODEL}",
                payload=batch.encode(),
                pod_identifier=pod,
                model_name=MODEL,
            )
        )
        self.event_pool.drain()

    def tokenize(self, prompt):
        return self.indexer.tokenization_pool.tokenize(prompt, MODEL, None)

    # -- read path (real HTTP) --

    def _post(self, path, obj):
        request = urllib.request.Request(
            self.base_url + path,
            data=json.dumps(obj).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(request, timeout=30) as response:
            assert response.status == 200
            return json.load(response)

    def score(self, prompt, model=MODEL):
        return self._post(
            "/score_completions", {"prompt": prompt, "model": model}
        )

    def score_chat(self, messages, model=MODEL):
        return self._post(
            "/score_chat_completions",
            {"model": model, "messages": messages},
        )


def boot(tokenizers_dir, register_chat=True):
    indexer = Indexer(
        IndexerConfig(
            token_processor_config=TokenProcessorConfig(
                block_size=BLOCK_SIZE
            ),
            kvblock_index_config=IndexConfig(
                in_memory_config=InMemoryIndexConfig(size=100_000)
            ),
            tokenizers_pool_config=TokenizationPoolConfig(
                workers=2, model_name=MODEL
            ),
            # Auto-discovery: no injected tokenizer; the composite's
            # local backend walks this dir (reference
            # TestCacheHitWithLocalTokenizer, e2e_test.go:388-433).
            local_tokenizers_dir=tokenizers_dir,
        )
    )
    if register_chat:
        indexer.chat_processor.register_tokenizer(
            MODEL, build_transformers_tokenizer()
        )
    indexer.run()
    event_pool = Pool(
        indexer.kv_block_index,
        indexer.token_processor,
        PoolConfig(concurrency=2),
    )
    event_pool.start()
    server = serve(indexer, host="127.0.0.1", port=0)
    base = f"http://127.0.0.1:{server.server_address[1]}"
    return ServiceFleet(indexer, event_pool, base), server


@pytest.fixture()
def fleet(tmp_path):
    tokenizers_dir = save_tokenizer_json(str(tmp_path), MODEL)
    booted, server = boot(tokenizers_dir)
    yield booted
    server.shutdown()
    booted.event_pool.shutdown()
    booted.indexer.shutdown()


class TestServiceE2E:
    def test_cache_hit(self, fleet):
        """e2e_test.go:116 TestCacheHit."""
        prompt = SENTENCE * 8
        tokens = fleet.tokenize(prompt)
        fleet.publish("pod-1", tokens)
        scores = fleet.score(prompt)
        assert scores["pod-1"] == pytest.approx(
            len(tokens) // BLOCK_SIZE
        )

    def test_cache_miss(self, fleet):
        """e2e_test.go:131 TestCacheMiss."""
        fleet.publish("pod-1", fleet.tokenize(SENTENCE * 8))
        scores = fleet.score("pack my box with five dozen liquor jugs . " * 8)
        assert scores == {}

    def test_prefix_reduction(self, fleet):
        """e2e_test.go:142 TestPrefixReduction: a shorter prompt sharing
        the stored prefix still hits, proportionally."""
        long_prompt = SENTENCE * 16
        fleet.publish("pod-1", fleet.tokenize(long_prompt))
        short = SENTENCE * 4
        scores = fleet.score(short)
        n_short_blocks = len(fleet.tokenize(short)) // BLOCK_SIZE
        assert scores["pod-1"] == pytest.approx(n_short_blocks)

    def test_prefix_expansion_churn(self, fleet):
        """e2e_test.go:178 TestPrefixExpansion: score caps at the stored
        prefix; storing the extension (chained off the parent hash)
        lifts the score on rescore."""
        full_prompt = SENTENCE * 16
        tokens = fleet.tokenize(full_prompt)
        half = len(tokens) // 2 // BLOCK_SIZE * BLOCK_SIZE
        first = fleet.publish("pod-1", tokens[:half])

        capped = fleet.score(full_prompt)
        assert capped["pod-1"] == pytest.approx(half // BLOCK_SIZE)

        fleet.publish("pod-1", tokens[half:], parent=first[-1])
        lifted = fleet.score(full_prompt)
        assert lifted["pod-1"] == pytest.approx(len(tokens) // BLOCK_SIZE)
        assert lifted["pod-1"] > capped["pod-1"]

    def test_long_prefix_expansion_4500_tokens(self, fleet):
        """e2e_test.go:214 TestLongPrefixExpansion at ~4.5k tokens.

        At this length the read path takes the prefix-store fast path
        (coverage >= min_prefix_overlap_ratio serves the cached token
        stream instead of re-tokenizing, pool.py — the reference's 0.8
        overlap design, pool.go:31-34), which may trail the full
        tokenization by a few chunk-boundary tokens; the score lands
        within 3% of the full block count, never above it."""
        prompt = SENTENCE * 450  # 4500 tokens with the word tokenizer
        tokens = fleet.tokenize(prompt)
        assert len(tokens) >= 4500
        fleet.publish("pod-long", tokens)
        n_blocks = len(tokens) // BLOCK_SIZE
        score = fleet.score(prompt)["pod-long"]
        assert 0.97 * n_blocks <= score <= n_blocks
        # Expansion past the stored prefix stays capped at it.
        extended = prompt + "how vexingly quick daft zebras jump . " * 50
        assert fleet.score(extended)["pod-long"] <= n_blocks

    def test_long_prompt_full_scenario(self, fleet):
        """Reference depth (e2e_test.go:214-251) at >280-block chains:
        prefix expansion, reduction, and mid-prompt divergence through
        the booted service, with BLOCK-ACCURATE hit-count asserts —
        each expectation computed from the token stream the service
        will actually use (full tokenization, or the prefix store's
        serve when its coverage engages the fast path) — plus the
        fast path PROVEN engaged via its metrics counter."""
        from llm_d_kv_cache_manager_tpu.metrics.collector import METRICS

        prompt = SENTENCE * 460  # 4600 tokens
        # NOTE: this helper tokenize populates the prefix store, so
        # every score below may be served from it; expectations are
        # computed accordingly (served_blocks_for), never hand-waved.
        tokens = fleet.tokenize(prompt)
        assert len(tokens) >= 4500
        n_blocks = len(tokens) // BLOCK_SIZE
        assert n_blocks > 280  # the reference's per-request chain scale

        def served_blocks_for(text):
            """Block count of the token stream the service will score
            ``text`` with: the prefix-store serve when its coverage
            engages the fast path, full tokenization otherwise."""
            pool = fleet.indexer.tokenization_pool
            served, coverage = (
                pool._prefix_store.find_longest_contained_tokens(
                    text, MODEL
                )
            )
            # The LIVE threshold, not a copy of its default: the test
            # must follow whatever serve path the pool actually takes.
            if coverage >= pool.config.min_prefix_overlap_ratio:
                return len(served) // BLOCK_SIZE
            return len(fleet.tokenize(text)) // BLOCK_SIZE

        # -- expansion: store the first half; the score caps exactly
        # there regardless of serve path (stored < served length).
        half = n_blocks // 2 * BLOCK_SIZE
        first = fleet.publish("pod-long", tokens[:half])
        assert fleet.score(prompt)["pod-long"] == pytest.approx(
            half // BLOCK_SIZE
        )
        # Store the second half chained on the parent hash: the score
        # lifts to exactly the served block count (== full tokenization
        # minus at most one trailing chunk).
        fleet.publish("pod-long", tokens[half:], parent=first[-1])
        expected = served_blocks_for(prompt)
        assert 0.97 * n_blocks <= expected <= n_blocks
        assert fleet.score(prompt)["pod-long"] == pytest.approx(expected)

        # -- reduction: a one-third prefix of the same prompt hits
        # exactly its served block count.
        short = SENTENCE * 150
        assert fleet.score(short)["pod-long"] == pytest.approx(
            served_blocks_for(short)
        )

        # -- mid-prompt divergence: same first half, different tail.
        # Shared coverage ~0.5 < 0.8 keeps it off the fast path, so
        # the cap is exactly the shared full blocks.
        divergent = (
            SENTENCE * 230
            + "pack my box with five dozen liquor jugs . " * 230
        )
        div_tokens = fleet.tokenize(divergent)
        shared = 0
        for a, b in zip(tokens, div_tokens):
            if a != b:
                break
            shared += 1
        assert shared >= 2000  # genuinely long shared prefix
        assert fleet.score(divergent)["pod-long"] == pytest.approx(
            shared // BLOCK_SIZE
        )

        # -- fast path PROVEN engaged (counter, not assumption) on a
        # full-prompt re-score.
        def fast_path_count():
            counter = METRICS.tokenization_prefix_fast_path
            return counter.collect()[0].samples[0].value

        before = fast_path_count()
        rescore = fleet.score(prompt)["pod-long"]
        after = fast_path_count()
        assert after > before, "prefix-store fast path never engaged"
        assert rescore == pytest.approx(expected)

    def test_chat_completions_e2e(self, fleet):
        """e2e_test.go:254 TestChatCompletionsE2E through the service."""
        messages = [
            {"role": "system", "content": "you are a helpful assistant ."},
            {"role": "user", "content": "hello world"},
        ]
        rendered = fleet.indexer.chat_processor.apply_chat_template(
            MODEL,
            _render_request(messages),
        )
        fleet.publish("pod-chat", fleet.tokenize(rendered))
        scores = fleet.score_chat(messages)
        assert scores.get("pod-chat", 0) > 0

    def test_long_chat_completions_e2e(self, fleet):
        """e2e_test.go:314 TestLongChatCompletionsE2E: a growing
        multi-turn conversation keeps hitting its stored prefix."""
        messages = [
            {"role": "system", "content": "you are a helpful assistant ."}
        ]
        for turn in range(12):
            messages.append(
                {"role": "user", "content": SENTENCE * 4}
            )
            messages.append(
                {"role": "assistant", "content": SENTENCE * 2}
            )
        rendered = fleet.indexer.chat_processor.apply_chat_template(
            MODEL, _render_request(messages)
        )
        tokens = fleet.tokenize(rendered)
        assert len(tokens) > 800  # genuinely long conversation
        fleet.publish("pod-chat", tokens)

        n_blocks = len(tokens) // BLOCK_SIZE
        full = fleet.score_chat(messages)["pod-chat"]
        # Prefix-store fast path may trail full tokenization by a few
        # chunk-boundary tokens (see test_long_prefix_expansion).
        assert 0.95 * n_blocks <= full <= n_blocks
        # One MORE turn: the prior conversation is the stored prefix.
        scores = fleet.score_chat(
            messages + [{"role": "user", "content": "hello world"}]
        )
        assert scores.get("pod-chat", 0) > 0

    def test_eviction_then_rescore(self, fleet):
        """Tail eviction reduces the score (lookup early-stops at the
        break); head eviction zeroes it; counterpart of the eviction
        churn the reference drives via BlockRemoved."""
        prompt = SENTENCE * 16
        tokens = fleet.tokenize(prompt)
        hashes = fleet.publish("pod-1", tokens)
        n_blocks = len(hashes)
        assert fleet.score(prompt)["pod-1"] == pytest.approx(n_blocks)

        fleet.evict("pod-1", hashes[n_blocks // 2:])
        reduced = fleet.score(prompt)
        assert reduced["pod-1"] == pytest.approx(n_blocks // 2)

        fleet.evict("pod-1", hashes[:1])
        assert fleet.score(prompt) == {}


def _render_request(messages):
    from llm_d_kv_cache_manager_tpu.preprocessing.chat_templating import (
        ApplyChatTemplateRequest,
    )

    return ApplyChatTemplateRequest(conversation=list(messages))


class TestTokenizerDiscoveryE2E:
    """e2e_test.go:388-485: tokenizer auto-discovery through the booted
    service — no tokenizer injected anywhere."""

    def test_plain_layout(self, tmp_path):
        tokenizers_dir = save_tokenizer_json(str(tmp_path), MODEL)
        fleet, server = boot(tokenizers_dir, register_chat=False)
        try:
            prompt = SENTENCE * 8
            tokens = fleet.tokenize(prompt)
            fleet.publish("pod-1", tokens)
            assert fleet.score(prompt)["pod-1"] > 0
        finally:
            server.shutdown()
            fleet.event_pool.shutdown()
            fleet.indexer.shutdown()

    def test_hf_cache_layout(self, tmp_path):
        """models--org--name/snapshots/<rev>/tokenizer.json, the layout
        a mounted HF cache volume presents (e2e_test.go:434)."""
        model = "test-org/chat-model"
        snapshot = os.path.join(
            str(tmp_path),
            "models--test-org--chat-model",
            "snapshots",
            "abcdef123",
        )
        os.makedirs(snapshot)
        build_fast_tokenizer().save(
            os.path.join(snapshot, "tokenizer.json")
        )
        fleet, server = boot(str(tmp_path), register_chat=False)
        try:
            prompt = SENTENCE * 8
            tokens = fleet.indexer.tokenization_pool.tokenize(
                prompt, model, None
            )
            n_blocks = len(tokens) // BLOCK_SIZE
            hashes = [0x9000 + i for i in range(n_blocks)]
            batch = EventBatch(
                ts=1.0,
                events=[
                    BlockStored(
                        block_hashes=hashes,
                        parent_block_hash=None,
                        token_ids=tokens[: n_blocks * BLOCK_SIZE],
                        block_size=BLOCK_SIZE,
                        medium="hbm",
                    )
                ],
            )
            fleet.event_pool.add_task(
                Message(
                    topic=f"kv@pod-hf@{model}",
                    payload=batch.encode(),
                    pod_identifier="pod-hf",
                    model_name=model,
                )
            )
            fleet.event_pool.drain()
            scores = fleet.score(prompt, model=model)
            assert scores["pod-hf"] == pytest.approx(n_blocks)
        finally:
            server.shutdown()
            fleet.event_pool.shutdown()
            fleet.indexer.shutdown()
