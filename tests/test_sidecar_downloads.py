"""Sidecar download hygiene (reference tokenizer_service/tokenizer.py:
60-178): tokenizer-related files only, ModelScope/HF source dispatch,
cache-first reuse, cleanup of failed downloads."""

from __future__ import annotations

import sys
import types

import pytest

from llm_d_kv_cache_manager_tpu.services import uds_tokenizer as sidecar


class FakeHub:
    """Stands in for huggingface_hub / modelscope snapshot_download.

    ``fail='partial'`` writes config.json and THEN raises — the
    interrupted-mid-snapshot case."""

    def __init__(self, fail=False):
        self.calls = []
        self.fail = fail

    def snapshot_download(self, model_id, local_dir, allow_patterns):
        import os

        self.calls.append(
            {
                "model_id": model_id,
                "local_dir": local_dir,
                "allow_patterns": list(allow_patterns),
            }
        )
        if self.fail == "partial":
            with open(os.path.join(local_dir, "config.json"), "w") as f:
                f.write("{}")
            with open(
                os.path.join(local_dir, "tokenizer.json"), "w"
            ) as f:
                f.write("{")  # truncated
            raise RuntimeError("network blip mid-download")
        if self.fail:
            raise RuntimeError("download failed")
        for name in ("config.json", "tokenizer.json"):
            with open(os.path.join(local_dir, name), "w") as f:
                f.write("{}")


@pytest.fixture
def fake_hf(monkeypatch):
    hub = FakeHub()
    module = types.ModuleType("huggingface_hub")
    module.snapshot_download = hub.snapshot_download
    monkeypatch.setitem(sys.modules, "huggingface_hub", module)
    monkeypatch.delenv("USE_MODELSCOPE", raising=False)
    return hub


@pytest.fixture
def fake_modelscope(monkeypatch):
    hub = FakeHub()
    module = types.ModuleType("modelscope")
    module.snapshot_download = hub.snapshot_download
    monkeypatch.setitem(sys.modules, "modelscope", module)
    monkeypatch.setenv("USE_MODELSCOPE", "true")
    return hub


class TestRemoteDetection:
    def test_hub_names_are_remote(self):
        assert sidecar.is_remote_model("meta-llama/Llama-3.1-8B")

    def test_paths_are_local(self, tmp_path):
        assert not sidecar.is_remote_model(str(tmp_path))
        assert not sidecar.is_remote_model("./models/x")
        assert not sidecar.is_remote_model("../x")

    def test_existing_relative_dir_is_local(self, tmp_path, monkeypatch):
        monkeypatch.chdir(tmp_path)
        (tmp_path / "local-model").mkdir()
        assert not sidecar.is_remote_model("local-model")


class TestFetch:
    def test_downloads_only_tokenizer_files(self, fake_hf, tmp_path):
        path = sidecar.fetch_tokenizer_files(
            "org/model", cache_dir=str(tmp_path)
        )
        assert path == str(tmp_path / "org" / "model")
        (call,) = fake_hf.calls
        assert call["allow_patterns"] == sidecar.TOKENIZER_FILE_PATTERNS
        # No weight patterns may ever sneak in.
        assert not any(
            "safetensors" in p or ".bin" in p or ".pt" in p
            for p in call["allow_patterns"]
        )

    def test_cache_hit_skips_download(self, fake_hf, tmp_path):
        sidecar.fetch_tokenizer_files("org/model", cache_dir=str(tmp_path))
        sidecar.fetch_tokenizer_files("org/model", cache_dir=str(tmp_path))
        assert len(fake_hf.calls) == 1  # second call reused the cache

    def test_modelscope_dispatch(self, fake_modelscope, tmp_path):
        sidecar.fetch_tokenizer_files("org/model", cache_dir=str(tmp_path))
        (call,) = fake_modelscope.calls
        assert call["model_id"] == "org/model"
        assert call["allow_patterns"] == sidecar.TOKENIZER_FILE_PATTERNS

    def test_local_path_passthrough(self, fake_hf, tmp_path):
        model_dir = tmp_path / "m"
        model_dir.mkdir()
        assert (
            sidecar.fetch_tokenizer_files(str(model_dir)) == str(model_dir)
        )
        assert fake_hf.calls == []

    def test_failed_download_removes_empty_dir(
        self, monkeypatch, tmp_path
    ):
        hub = FakeHub(fail=True)
        module = types.ModuleType("huggingface_hub")
        module.snapshot_download = hub.snapshot_download
        monkeypatch.setitem(sys.modules, "huggingface_hub", module)
        monkeypatch.delenv("USE_MODELSCOPE", raising=False)
        with pytest.raises(RuntimeError):
            sidecar.fetch_tokenizer_files(
                "org/broken", cache_dir=str(tmp_path)
            )
        # The empty dir must not fake a future cache hit.
        assert not (tmp_path / "org" / "broken").exists()

    def test_env_cache_dir(self, fake_hf, tmp_path, monkeypatch):
        monkeypatch.setenv("TOKENIZER_CACHE_DIR", str(tmp_path / "env"))
        path = sidecar.fetch_tokenizer_files("org/model")
        assert path.startswith(str(tmp_path / "env"))

    def test_partial_download_is_not_a_cache_hit(
        self, monkeypatch, tmp_path
    ):
        """A download interrupted mid-snapshot must not leave files at
        the cache path (they'd satisfy the cached check forever)."""
        hub = FakeHub(fail="partial")
        module = types.ModuleType("huggingface_hub")
        module.snapshot_download = hub.snapshot_download
        monkeypatch.setitem(sys.modules, "huggingface_hub", module)
        monkeypatch.delenv("USE_MODELSCOPE", raising=False)
        with pytest.raises(RuntimeError):
            sidecar.fetch_tokenizer_files(
                "org/model", cache_dir=str(tmp_path)
            )
        assert not (tmp_path / "org" / "model").exists()
        # A retry re-downloads instead of reusing the wreckage.
        good = FakeHub()
        module.snapshot_download = good.snapshot_download
        sidecar.fetch_tokenizer_files("org/model", cache_dir=str(tmp_path))
        assert len(good.calls) == 1

    def test_sentencepiece_only_cache_hit(self, fake_hf, tmp_path):
        """config.json + tokenizer.model (no tokenizer.json) counts as
        cached — sentencepiece-only models must not re-download."""
        model_dir = tmp_path / "org" / "sp"
        model_dir.mkdir(parents=True)
        (model_dir / "config.json").write_text("{}")
        (model_dir / "tokenizer.model").write_text("sp")
        path = sidecar.fetch_tokenizer_files(
            "org/sp", cache_dir=str(tmp_path)
        )
        assert path == str(model_dir) and fake_hf.calls == []

    @pytest.mark.parametrize(
        "bad",
        [
            "a/../../../../etc",
            "../x",  # local-looking but guard both layers
            "org/..",
            "org/.",
            "a/b/c",
            "org//model",
            "org/mo del",
        ],
    )
    def test_traversal_identifiers_rejected(self, fake_hf, tmp_path, bad):
        if not sidecar.is_remote_model(bad):
            return  # handled as a local path, never touches the cache
        with pytest.raises(ValueError):
            sidecar.fetch_tokenizer_files(bad, cache_dir=str(tmp_path))
        assert fake_hf.calls == []


class TestRegistryLoader:
    def test_registry_uses_injected_loader(self):
        loads = []

        def loader(name):
            loads.append(name)
            return object()

        registry = sidecar.TokenizerRegistry(loader=loader)
        first = registry.get("org/m")
        second = registry.get("org/m")
        assert first is second and loads == ["org/m"]
