"""SLO engine unit suite (obs/slo.py): window math, burn rates,
envelope state transitions, source constructors, and the
envelope-consistency checker chaos cells rely on."""

import time

import pytest

from llm_d_kv_cache_manager_tpu.metrics.collector import METRICS
from llm_d_kv_cache_manager_tpu.obs.slo import (
    STATE_DEGRADED,
    STATE_HEALTHY,
    STATE_VIOLATED,
    SloEngine,
    SloSpec,
    counter_label_total,
    default_fleet_slos,
    envelope_violations,
    histogram_latency_source,
    labeled_gauge_max,
    labeled_gauge_sum,
)


class _RatioFeed:
    """Mutable cumulative (good, total) source."""

    def __init__(self):
        self.good = 0.0
        self.total = 0.0

    def add(self, good, bad=0):
        self.good += good
        self.total += good + bad

    def __call__(self):
        return self.good, self.total


class _ValueFeed:
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self):
        return (self.value, 0.0)


def ratio_engine(objective=0.9, bound=0.5, fast=10.0, slow=100.0):
    engine = SloEngine(window_fast_s=fast, window_slow_s=slow)
    feed = _RatioFeed()
    engine.register(
        SloSpec(
            "sli", kind="ratio", objective=objective, degraded_bound=bound
        ),
        feed,
    )
    return engine, feed


class TestSpecValidation:
    def test_ratio_bounds_ordering(self):
        with pytest.raises(ValueError):
            SloSpec("x", kind="ratio", objective=0.5, degraded_bound=0.9
                    ).validate()

    def test_gauge_bounds_ordering(self):
        with pytest.raises(ValueError):
            SloSpec("x", kind="gauge", objective=10, degraded_bound=5
                    ).validate()

    def test_unknown_kind(self):
        with pytest.raises(ValueError):
            SloSpec("x", kind="p99").validate()

    def test_duplicate_sli_rejected(self):
        engine, _ = ratio_engine()
        with pytest.raises(ValueError):
            engine.register(SloSpec("sli"), lambda: (0, 0))

    def test_window_ordering_rejected(self):
        with pytest.raises(ValueError):
            SloEngine(window_fast_s=100, window_slow_s=10)


class TestWindowMath:
    def test_no_data_is_healthy_and_flagged(self):
        engine, _ = ratio_engine()
        view = engine.evaluate(now=100.0)["slis"]["sli"]
        assert view["state"] == STATE_HEALTHY
        assert view["no_data"] is True
        assert view["value"] is None

    def test_delta_is_windowed_not_lifetime(self):
        """Old badness outside the window must not count against the
        current fraction."""
        engine, feed = ratio_engine(objective=0.9, bound=0.5)
        feed.add(good=0, bad=100)  # terrible history
        engine.sample(now=0.0)
        feed.add(good=100)  # perfect recent traffic
        engine.sample(now=5.0)
        feed.add(good=100)
        engine.sample(now=9.0)
        view = engine.evaluate(now=9.0)["slis"]["sli"]
        # Fast window (10s) baseline is the t=0 sample: deltas are the
        # 200 good / 200 total recent requests.
        assert view["value"] == 1.0
        assert view["state"] == STATE_HEALTHY

    def test_burn_rate_math(self):
        engine, feed = ratio_engine(objective=0.9, bound=0.0)
        engine.sample(now=0.0)
        feed.add(good=80, bad=20)  # 20% bad over a 10% budget
        engine.sample(now=5.0)
        view = engine.evaluate(now=5.0)["slis"]["sli"]
        assert view["burn_fast"] == pytest.approx(2.0)
        assert view["state"] == STATE_DEGRADED

    def test_engine_younger_than_window_uses_oldest_sample(self):
        engine, feed = ratio_engine(fast=1000.0, slow=10000.0)
        engine.sample(now=0.0)
        feed.add(good=10)
        engine.sample(now=1.0)
        view = engine.evaluate(now=1.0)["slis"]["sli"]
        assert view["value"] == 1.0

    def test_counter_reset_clamps(self):
        """A registry restart (cumulative counters falling) must not
        produce a negative fraction."""
        engine, feed = ratio_engine()
        feed.add(good=100)
        engine.sample(now=0.0)
        feed.good = 10.0
        feed.total = 10.0
        engine.sample(now=5.0)
        view = engine.evaluate(now=5.0)["slis"]["sli"]
        assert view["no_data"] is True or 0.0 <= view["value"] <= 1.0


class TestStateTransitions:
    def test_ratio_healthy_degraded_violated_and_back(self):
        engine, feed = ratio_engine(objective=0.9, bound=0.5, fast=10,
                                    slow=20)
        now = 0.0
        engine.sample(now=now)
        feed.add(good=99, bad=1)
        now += 5
        engine.sample(now=now)
        assert engine.evaluate(now=now)["slis"]["sli"]["state"] == (
            STATE_HEALTHY
        )
        # Objective breach inside the declared bound -> degraded.
        feed.add(good=70, bad=30)
        now += 5
        engine.sample(now=now)
        payload = engine.evaluate(now=now)
        assert payload["slis"]["sli"]["state"] == STATE_DEGRADED
        assert payload["state"] == STATE_DEGRADED
        assert envelope_violations(payload) == []
        # Bound breach -> violated; the consistency checker flags it.
        now += 25  # age the good history out of both windows
        engine.sample(now=now)
        feed.add(good=10, bad=90)
        now += 5
        engine.sample(now=now)
        payload = engine.evaluate(now=now)
        assert payload["slis"]["sli"]["state"] == STATE_VIOLATED
        assert payload["state"] == STATE_VIOLATED
        assert envelope_violations(payload)
        # Recovery: good traffic ages the badness out again.
        now += 25
        engine.sample(now=now)
        feed.add(good=100)
        now += 5
        engine.sample(now=now)
        assert engine.evaluate(now=now)["slis"]["sli"]["state"] == (
            STATE_HEALTHY
        )

    def test_slow_window_bleed_degrades_despite_healthy_fast(self):
        engine, feed = ratio_engine(objective=0.9, bound=0.1, fast=10,
                                    slow=100)
        engine.sample(now=0.0)
        feed.add(good=50, bad=50)  # bad burst, old
        engine.sample(now=50.0)
        feed.add(good=100)  # recent traffic perfect
        engine.sample(now=95.0)
        view = engine.evaluate(now=95.0)["slis"]["sli"]
        assert view["value"] == 1.0  # fast window is clean
        assert view["value_slow"] < 0.9
        assert view["state"] == STATE_DEGRADED

    def test_gauge_states(self):
        engine = SloEngine(window_fast_s=10, window_slow_s=100)
        feed = _ValueFeed(0.0)
        engine.register(
            SloSpec("g", kind="gauge", objective=2.0, degraded_bound=5.0,
                    gauge_agg="last"),
            feed,
        )
        engine.sample(now=0.0)
        assert engine.evaluate(now=0.0)["slis"]["g"]["state"] == (
            STATE_HEALTHY
        )
        feed.value = 3.0
        engine.sample(now=1.0)
        assert engine.evaluate(now=1.0)["slis"]["g"]["state"] == (
            STATE_DEGRADED
        )
        feed.value = 6.0
        engine.sample(now=2.0)
        payload = engine.evaluate(now=2.0)
        assert payload["slis"]["g"]["state"] == STATE_VIOLATED
        assert envelope_violations(payload)

    def test_gauge_max_agg_holds_spikes_for_the_window(self):
        engine = SloEngine(window_fast_s=10, window_slow_s=100)
        feed = _ValueFeed(9.0)
        engine.register(
            SloSpec("g", kind="gauge", objective=2.0,
                    degraded_bound=20.0),
            feed,
        )
        engine.sample(now=0.0)
        feed.value = 0.0
        engine.sample(now=5.0)
        # max agg: the 9.0 spike is still inside the fast window.
        assert engine.evaluate(now=5.0)["slis"]["g"]["state"] == (
            STATE_DEGRADED
        )
        # Once the spike ages out of the fast window the current value
        # (0.0, via the last-sample fallback) decides.
        assert engine.evaluate(now=50.0)["slis"]["g"]["state"] == (
            STATE_HEALTHY
        )

    def test_rate_kind_windows_counter_deltas(self):
        engine = SloEngine(window_fast_s=10, window_slow_s=100)
        feed = _ValueFeed(0.0)
        engine.register(
            SloSpec("failovers", kind="rate", objective=0.0,
                    degraded_bound=2.0),
            feed,
        )
        engine.sample(now=0.0)
        engine.sample(now=5.0)
        assert engine.evaluate(now=5.0)["slis"]["failovers"]["state"] == (
            STATE_HEALTHY
        )
        feed.value = 1.0  # one failover in the fast window
        engine.sample(now=6.0)
        assert engine.evaluate(now=6.0)["slis"]["failovers"]["state"] == (
            STATE_DEGRADED
        )
        feed.value = 4.0  # three more: past the declared bound
        engine.sample(now=7.0)
        assert engine.evaluate(now=7.0)["slis"]["failovers"]["state"] == (
            STATE_VIOLATED
        )
        # The window slides: with no NEW failovers the delta decays.
        engine.sample(now=30.0)
        assert engine.evaluate(now=30.0)["slis"]["failovers"][
            "state"
        ] == STATE_HEALTHY


class TestEngineSurface:
    def test_overall_is_worst_sli(self):
        engine = SloEngine(window_fast_s=10, window_slow_s=100)
        good = _ValueFeed(0.0)
        bad = _ValueFeed(100.0)
        engine.register(
            SloSpec("ok", kind="gauge", objective=1, degraded_bound=2),
            good,
        )
        engine.register(
            SloSpec("broken", kind="gauge", objective=1,
                    degraded_bound=2),
            bad,
        )
        engine.sample(now=0.0)
        payload = engine.evaluate(now=0.0)
        assert payload["state"] == STATE_VIOLATED
        assert payload["slis"]["ok"]["state"] == STATE_HEALTHY

    def test_raising_source_is_counted_not_fatal(self):
        engine = SloEngine(window_fast_s=10, window_slow_s=100)

        def explode():
            raise RuntimeError("source down")

        engine.register(SloSpec("s", kind="gauge", objective=1,
                                degraded_bound=2), explode)
        payload = engine.status(now=0.0)
        assert payload["slis"]["s"]["state"] == STATE_HEALTHY
        assert payload["source_errors"]["s"] >= 1

    def test_none_source_means_no_data(self):
        engine = SloEngine(window_fast_s=10, window_slow_s=100)
        engine.register(
            SloSpec("s", kind="gauge", objective=1, degraded_bound=2),
            lambda: None,
        )
        engine.sample(now=0.0)
        assert engine.evaluate(now=0.0)["slis"]["s"]["no_data"] is True

    def test_healthz_block_lists_unhealthy_slis(self):
        engine = SloEngine(window_fast_s=10, window_slow_s=100)
        engine.register(
            SloSpec("burning", kind="gauge", objective=0.0,
                    degraded_bound=10.0),
            _ValueFeed(5.0),
        )
        block = engine.healthz_block()
        assert block["state"] == STATE_DEGRADED
        assert block["degraded"] == ["burning"]

    def test_state_gauge_exported(self):
        from llm_d_kv_cache_manager_tpu.metrics.collector import (
            gauge_value,
        )

        engine = SloEngine(window_fast_s=10, window_slow_s=100)
        engine.register(
            SloSpec("exported_sli", kind="gauge", objective=0.0,
                    degraded_bound=1.0),
            _ValueFeed(0.5),
        )
        engine.sample(now=0.0)
        engine.evaluate(now=0.0)
        sample_value = None
        for metric in METRICS.slo_state.collect():
            for sample in metric.samples:
                if sample.labels.get("sli") == "exported_sli":
                    sample_value = sample.value
        assert sample_value == 1.0  # degraded
        assert gauge_value is not None  # helper importable

    def test_sample_retention_is_bounded(self):
        engine, feed = ratio_engine(fast=10, slow=20)
        for i in range(1000):
            feed.add(good=1)
            engine.sample(now=float(i))
        view = engine.evaluate(now=999.0)["slis"]["sli"]
        assert view["samples"] <= 30  # pruned to ~slow window span

    def test_background_loop_starts_and_stops(self):
        engine, feed = ratio_engine()
        feed.add(good=5)
        engine.start(poll_interval_s=0.01)
        deadline = time.time() + 5
        while time.time() < deadline:
            if engine.evaluate()["slis"]["sli"]["samples"] >= 2:
                break
            time.sleep(0.01)
        engine.close()
        assert engine.evaluate()["slis"]["sli"]["samples"] >= 2

    def test_start_after_close_restarts_polling(self):
        """close() sets the stop flag; a later start() must clear it
        or the new thread exits on its first wait and polling silently
        dies."""
        engine, feed = ratio_engine()
        engine.start(poll_interval_s=0.01)
        engine.close()
        before = engine.evaluate()["slis"]["sli"]["samples"]
        feed.add(good=1)
        engine.start(poll_interval_s=0.01)
        deadline = time.time() + 5
        grew = False
        while time.time() < deadline:
            if engine.evaluate()["slis"]["sli"]["samples"] > before:
                grew = True
                break
            time.sleep(0.01)
        engine.close()
        assert grew, "restarted loop never sampled"

    def test_healthz_block_serves_cached_evaluation(self):
        """A liveness probe must not re-sample every source per hit:
        healthz_block serves the LAST evaluation (with its timestamp),
        falling back to a full pass only when none has run."""
        engine = SloEngine(window_fast_s=10, window_slow_s=100)
        calls = {"n": 0}

        def source():
            calls["n"] += 1
            return (0.0, 0.0)

        engine.register(
            SloSpec("s", kind="gauge", objective=1, degraded_bound=2),
            source,
        )
        engine.sample(now=0.0)
        engine.evaluate(now=0.0)
        sampled = calls["n"]
        block = engine.healthz_block()
        assert block["evaluated_unix"] == 0.0
        assert calls["n"] == sampled  # no re-sampling on the hit


class TestSources:
    def test_histogram_latency_source_good_total(self):
        from prometheus_client import CollectorRegistry, Histogram

        registry = CollectorRegistry()
        hist = Histogram(
            "t_latency_seconds", "t", registry=registry,
            buckets=(0.01, 0.1, 1.0),
        )
        source = histogram_latency_source(hist, 0.1)
        hist.observe(0.005)
        hist.observe(0.05)
        hist.observe(0.5)
        good, total = source()
        assert total == 3.0
        assert good == 2.0  # <= the 0.1 bucket

    def test_histogram_threshold_between_buckets_rounds_down(self):
        """A threshold between bounds must undercount good, never
        round up to the next bucket (a service 60% over the objective
        would otherwise read 100% healthy)."""
        from prometheus_client import CollectorRegistry, Histogram

        registry = CollectorRegistry()
        hist = Histogram(
            "t3_latency_seconds", "t", registry=registry,
            buckets=(0.1, 0.25),
        )
        hist.observe(0.24)  # over a 0.15 objective, inside le=0.25
        good, total = histogram_latency_source(hist, 0.15)()
        assert (good, total) == (0.0, 1.0)  # NOT (1.0, 1.0)
        hist.observe(0.05)
        good, total = histogram_latency_source(hist, 0.15)()
        assert (good, total) == (1.0, 2.0)

    def test_histogram_threshold_above_finite_buckets_clamps_down(self):
        """The +Inf bucket must never satisfy the threshold — it would
        report a 100%-healthy latency SLI however slow the service
        got.  Past the widest finite bucket the source clamps DOWN
        (good undercounts, never overcounts)."""
        from prometheus_client import CollectorRegistry, Histogram

        registry = CollectorRegistry()
        hist = Histogram(
            "t2_latency_seconds", "t", registry=registry,
            buckets=(0.01,),
        )
        hist.observe(5.0)  # lands only in +Inf
        good, total = histogram_latency_source(hist, 100.0)()
        assert (good, total) == (0.0, 1.0)
        hist.observe(0.005)  # inside the widest finite bucket
        good, total = histogram_latency_source(hist, 100.0)()
        assert (good, total) == (1.0, 2.0)

    def test_counter_label_total_filters(self):
        from prometheus_client import CollectorRegistry, Counter

        registry = CollectorRegistry()
        counter = Counter(
            "t_requests", "t", ("outcome",), registry=registry
        )
        counter.labels(outcome="hit").inc(3)
        counter.labels(outcome="miss").inc(2)
        assert counter_label_total(counter, outcome="hit") == 3.0
        assert counter_label_total(counter) == 5.0

    def test_labeled_gauge_sum_and_max(self):
        from prometheus_client import CollectorRegistry, Gauge

        registry = CollectorRegistry()
        gauge = Gauge("t_backlog", "t", ("pod",), registry=registry)
        gauge.labels(pod="a").set(3)
        gauge.labels(pod="b").set(7)
        assert labeled_gauge_sum(gauge) == 10.0
        assert labeled_gauge_max(gauge) == 7.0


class TestDefaultFleetSlos:
    def test_constructs_and_evaluates_against_live_metrics(self):
        engine = default_fleet_slos(window_fast_s=1.0, window_slow_s=2.0)
        payload = engine.status()
        assert "score_latency" in payload["slis"]
        assert "hit_rate" in payload["slis"]
        assert payload["state"] in (
            STATE_HEALTHY, STATE_DEGRADED, STATE_VIOLATED,
        )

    def test_membership_slis_follow_a_kill(self):
        from llm_d_kv_cache_manager_tpu.cluster import LocalCluster

        cluster = LocalCluster()
        try:
            engine = default_fleet_slos(
                window_fast_s=60.0,
                window_slow_s=120.0,
                membership=cluster.membership,
            )
            now = time.time()
            engine.sample(now=now)
            payload = engine.evaluate(now=now)
            assert payload["slis"]["replicas_dead"]["state"] == (
                STATE_HEALTHY
            )
            cluster.kill("replica-0")
            engine.sample(now=now + 1)
            payload = engine.evaluate(now=now + 1)
            assert payload["slis"]["replicas_dead"]["state"] == (
                STATE_DEGRADED
            )
            assert payload["slis"]["failovers"]["state"] == (
                STATE_DEGRADED
            )
            # Degraded-with-bound, not violated, for the SLIs this
            # test controls.  (Other default SLIs read process-global
            # gauges — e.g. pod backlog — that unrelated tests may
            # have inflated, so the check is scoped, not engine-wide.)
            violations = envelope_violations(payload)
            assert not [
                v
                for v in violations
                if v.startswith(("replicas_dead", "failovers"))
            ], violations
        finally:
            cluster.close()

    def test_hit_rate_objective_zero_is_informational(self):
        engine = default_fleet_slos(window_fast_s=1.0, window_slow_s=2.0)
        view = engine.status()["slis"]["hit_rate"]
        assert view["objective"] == 0.0
        assert view["state"] == STATE_HEALTHY
