"""Staging-engine tests: parity vs the one-shot oracle, CPU fallback,
lane backpressure (watchdog-armed), write-side RTT stamping, atomic
file layout, and the staged demotion target's real byte moves."""

import os
import threading
import time

import numpy as np
import pytest

from llm_d_kv_cache_manager_tpu.models.kv_cache_pool import (
    KVCachePool,
    KVCachePoolConfig,
)
from llm_d_kv_cache_manager_tpu.native.engine import JobStatus, _PythonEngine
from llm_d_kv_cache_manager_tpu.offload.host_tier import HostTierCache
from llm_d_kv_cache_manager_tpu.offload.spec import (
    TPUOffloadConnector,
    TPUOffloadSpec,
)
from llm_d_kv_cache_manager_tpu.offload.staging import StagingBudget
from llm_d_kv_cache_manager_tpu.offload.staging_engine import (
    StagingConfig,
    StagingEngine,
    StagingSaturated,
)
from llm_d_kv_cache_manager_tpu.offload.worker import (
    DeviceToStorageHandler,
    group_blocks_per_file,
    host_dtype,
)
from llm_d_kv_cache_manager_tpu.tiering.staged_target import (
    StagedDemotionTarget,
)

POOL_CONFIG = KVCachePoolConfig(
    num_layers=3,
    num_blocks=32,
    block_size=8,
    num_kv_heads=2,
    head_dim=16,
    dtype="bfloat16",
)


def make_connector(tmp_path, staging_lanes=0, pool=None, event_sink=None,
                   subdir="kv"):
    spec = TPUOffloadSpec(
        shared_storage_path=str(tmp_path / subdir),
        model_name="llama-3-8b",
        device_block_size=8,
        offloaded_block_size=16,  # 2 device blocks per file
        threads_per_chip=2,
        staging_lanes=staging_lanes,
    )
    pool = pool or KVCachePool(POOL_CONFIG)
    return TPUOffloadConnector(spec, pool, event_sink=event_sink), pool


def fill_pool_blocks(pool, block_ids, seed=0):
    rng = np.random.default_rng(seed)
    c = pool.config
    written = {}
    for block_id in block_ids:
        data = rng.standard_normal(
            (c.num_layers, 2, c.block_size, c.num_kv_heads, c.head_dim)
        ).astype(host_dtype(c.dtype))
        pool.write_block(block_id, data)
        written[block_id] = data
    return written


def read_tree(root):
    """{relative path: bytes} of every file under root."""
    out = {}
    for dirpath, _, files in os.walk(root):
        for name in files:
            path = os.path.join(dirpath, name)
            with open(path, "rb") as handle:
                out[os.path.relpath(path, root)] = handle.read()
    return out


class TestStagedParity:
    """Staged path ≡ one-shot path: same bytes on disk, same pool."""

    def test_disk_bytes_bit_identical(self, tmp_path):
        block_ids = [3, 4, 7, 9, 11]  # partial tail group included
        hashes = [0xA, 0xB, 0xC]
        pool = KVCachePool(POOL_CONFIG)
        fill_pool_blocks(pool, block_ids)

        oneshot, _ = make_connector(tmp_path, 0, pool=pool, subdir="one")
        staged, _ = make_connector(tmp_path, 2, pool=pool, subdir="two")
        assert staged.staging is not None
        groups = group_blocks_per_file(hashes, block_ids, 2)
        oneshot.store_handler.transfer_async(1, groups)
        staged.store_handler.transfer_async(1, groups)
        assert oneshot.store_handler.wait(1) == JobStatus.SUCCEEDED
        assert staged.store_handler.wait(1) == JobStatus.SUCCEEDED

        one = read_tree(str(tmp_path / "one"))
        two = read_tree(str(tmp_path / "two"))
        assert one.keys() == two.keys() and len(one) == 3
        for rel in one:
            assert one[rel] == two[rel], f"byte drift in {rel}"
        oneshot.close()
        staged.close()

    def test_scatter_bit_identical(self, tmp_path):
        block_ids = [1, 2, 5, 6, 8]
        hashes = [0x1, 0x2, 0x3]
        source = KVCachePool(POOL_CONFIG)
        fill_pool_blocks(source, block_ids)
        writer, _ = make_connector(tmp_path, 0, pool=source)
        writer.store_handler.transfer_async(
            1, group_blocks_per_file(hashes, block_ids, 2)
        )
        assert writer.store_handler.wait(1) == JobStatus.SUCCEEDED

        target_ids = [20, 21, 22, 23, 24]
        load_groups = group_blocks_per_file(hashes, target_ids, 2)
        pools = {}
        for lanes in (0, 2):
            pool = KVCachePool(POOL_CONFIG)
            reader, _ = make_connector(tmp_path, lanes, pool=pool)
            reader.load_handler.transfer_async(2, load_groups)
            assert reader.load_handler.wait(2) == JobStatus.SUCCEEDED
            pools[lanes] = pool.gather_to_host(target_ids)
            reader.close()
        np.testing.assert_array_equal(pools[0], pools[2])
        np.testing.assert_array_equal(
            pools[2], source.gather_to_host(block_ids)
        )
        writer.close()

    def test_polling_path_routes_staged_parent(self, tmp_path):
        events = []
        connector, pool = make_connector(
            tmp_path,
            2,
            event_sink=lambda hashes, medium: events.append(
                (tuple(hashes), medium)
            ),
        )
        fill_pool_blocks(pool, [0, 1])
        connector.store_handler.transfer_async(
            10, group_blocks_per_file([0xC], [0, 1], 2)
        )
        deadline = time.monotonic() + 10
        finished = []
        while time.monotonic() < deadline and not finished:
            finished = connector.get_finished()
            time.sleep(0.01)
        # The raw engine sub-job id must never surface — only the
        # parent the caller submitted.
        assert finished == [(10, JobStatus.SUCCEEDED)]
        assert events == [((0xC,), "shared_storage")]

        connector.load_handler.transfer_async(
            11, group_blocks_per_file([0xC], [5, 6], 2)
        )
        deadline = time.monotonic() + 10
        finished = []
        while time.monotonic() < deadline and not finished:
            finished = connector.get_finished()
            time.sleep(0.01)
        assert finished == [(11, JobStatus.SUCCEEDED)]
        np.testing.assert_array_equal(
            pool.gather_to_host([5, 6]), pool.gather_to_host([0, 1])
        )
        connector.close()

    def test_staged_load_missing_file_fails(self, tmp_path):
        connector, _ = make_connector(tmp_path, 2)
        connector.load_handler.transfer_async(
            20, group_blocks_per_file([0xDEAD], [1, 2], 2)
        )
        assert connector.load_handler.wait(20) == JobStatus.FAILED
        connector.close()

    def test_zero_group_staged_load_completes(self, tmp_path):
        connector, _ = make_connector(tmp_path, 1)
        connector.load_handler.transfer_async(30, [])
        assert connector.load_handler.wait(30) == JobStatus.SUCCEEDED
        connector.close()

    def test_staged_host_tier_hit_skips_file(self, tmp_path):
        """A host-cached group scatters immediately; only misses read
        files, and the RTT observer sees only the file bytes."""
        connector, pool = make_connector(tmp_path, 0)
        block_ids = [1, 2, 3, 4]
        fill_pool_blocks(pool, block_ids)
        connector.store_handler.transfer_async(
            1, group_blocks_per_file([0xA, 0xB], block_ids, 2)
        )
        assert connector.store_handler.wait(1) == JobStatus.SUCCEEDED

        from llm_d_kv_cache_manager_tpu.offload.worker import (
            StorageToDeviceHandler,
        )

        cache = HostTierCache(1 << 20)
        assert cache.put(0xA, pool.gather_block_major([1, 2]))
        staging = StagingEngine(
            pool, connector.engine, connector.file_mapper, 2,
            StagingConfig(lanes_per_chip=1),
        )
        observed = []
        loader = StorageToDeviceHandler(
            pool,
            connector.engine,
            connector.file_mapper,
            host_cache=cache,
            rtt_observer=lambda nbytes, s: observed.append((nbytes, s)),
            staging=staging,
        )
        loader.transfer_async(
            5, group_blocks_per_file([0xA, 0xB], [20, 21, 22, 23], 2)
        )
        assert loader.wait(5) == JobStatus.SUCCEEDED
        np.testing.assert_array_equal(
            pool.gather_to_host([20, 21, 22, 23]),
            pool.gather_to_host(block_ids),
        )
        assert len(observed) == 1
        nbytes, seconds = observed[0]
        assert nbytes == 2 * pool.block_nbytes  # group 0xB only
        assert seconds > 0
        connector.close()


class TestCpuFallback:
    def test_fallback_when_pinned_unsupported(self, tmp_path):
        """use_pinned=None probes the pool; forcing False must keep
        the pipeline byte-correct through plain reusable slots."""
        pool = KVCachePool(POOL_CONFIG)
        connector, _ = make_connector(tmp_path, 0, pool=pool)
        staging = StagingEngine(
            pool, connector.engine, connector.file_mapper, 2,
            StagingConfig(lanes_per_chip=1, use_pinned=False),
        )
        assert not staging.uses_pinned
        fill_pool_blocks(pool, [0, 1, 2])
        staging.store(
            1, group_blocks_per_file([0xA, 0xB], [0, 1, 2], 2)
        )
        assert staging.wait(1) == JobStatus.SUCCEEDED
        staging.job_stats(1)
        # Slot reuse across two groups must not corrupt the first
        # file (written before the slot was reused).
        path = connector.file_mapper.get_file_name(0xA)
        expected = pool.gather_block_major([0, 1])
        with open(path, "rb") as handle:
            on_disk = np.frombuffer(
                handle.read(), dtype=expected.dtype
            ).reshape(expected.shape)
        np.testing.assert_array_equal(on_disk, expected)
        connector.close()

    def test_auto_probe_matches_pool(self, tmp_path):
        pool = KVCachePool(POOL_CONFIG)
        connector, _ = make_connector(tmp_path, 1, pool=pool)
        assert connector.staging.uses_pinned == pool.pinned_host
        connector.close()


class TestBackpressure:
    def test_lane_saturation_raises_not_deadlocks(self, tmp_path):
        connector, pool = make_connector(tmp_path, 0)
        staging = StagingEngine(
            pool, connector.engine, connector.file_mapper, 2,
            StagingConfig(lanes_per_chip=1, lane_wait_s=0.2),
        )
        lane = staging._acquire_lane()
        t0 = time.monotonic()
        with pytest.raises(StagingSaturated):
            staging._acquire_lane()
        assert time.monotonic() - t0 < 5
        staging._release_lane(lane)
        # After release the lane is acquirable again.
        staging._release_lane(staging._acquire_lane())
        connector.close()

    def test_saturation_raise_completes_job_as_failed(self, tmp_path):
        """A StagingSaturated raise must not strand the job: it still
        completes (FAILED) so the handler's harvest releases budget
        and pending state, and the id becomes reusable."""
        pool = KVCachePool(POOL_CONFIG)
        connector, _ = make_connector(tmp_path, 0, pool=pool)
        staging = StagingEngine(
            pool, connector.engine, connector.file_mapper, 2,
            StagingConfig(lanes_per_chip=1, lane_wait_s=0.2),
        )
        budget = StagingBudget(1 << 30)
        handler = DeviceToStorageHandler(
            pool,
            connector.engine,
            connector.file_mapper,
            staging_budget=budget,
            staging=staging,
        )
        fill_pool_blocks(pool, [0, 1])
        lane = staging._acquire_lane()  # wedge the only lane
        with pytest.raises(StagingSaturated):
            handler.transfer_async(
                7, group_blocks_per_file([0xE], [0, 1], 2)
            )
        # The job surfaced as FAILED and the harvest releases budget.
        assert handler.wait(7) == JobStatus.FAILED
        assert budget.in_flight_bytes == 0
        staging._release_lane(lane)
        # The id is reusable and the path is healthy again.
        handler.transfer_async(
            7, group_blocks_per_file([0xE], [0, 1], 2)
        )
        assert handler.wait(7) == JobStatus.SUCCEEDED
        connector.close()

    def test_concurrent_jobs_with_budget_no_deadlock(self, tmp_path):
        """Lane saturation + a tight StagingBudget together: every
        submitter completes (watchdog: the test fails by timeout
        assertion, not by hanging)."""
        pool = KVCachePool(POOL_CONFIG)
        connector, _ = make_connector(tmp_path, 0, pool=pool)
        staging = StagingEngine(
            pool, connector.engine, connector.file_mapper, 2,
            StagingConfig(
                lanes_per_chip=1, slots_per_lane=1, lane_wait_s=30.0
            ),
        )
        # Budget fits ~2 concurrent jobs of 2 blocks each.
        budget = StagingBudget(4 * pool.block_nbytes)
        handler = DeviceToStorageHandler(
            pool,
            connector.engine,
            connector.file_mapper,
            staging_budget=budget,
            staging=staging,
        )
        fill_pool_blocks(pool, list(range(8)))
        errors = []
        done = []

        def submit(worker_idx):
            try:
                for j in range(3):
                    job_id = worker_idx * 100 + j
                    ids = [(worker_idx * 3 + j) * 2 % 8,
                           ((worker_idx * 3 + j) * 2 + 1) % 8]
                    handler.transfer_async(
                        job_id,
                        group_blocks_per_file([0x500 + job_id], ids, 2),
                    )
                    assert handler.wait(job_id) == JobStatus.SUCCEEDED
                done.append(worker_idx)
            except Exception as exc:  # noqa: BLE001
                errors.append(exc)

        threads = [
            threading.Thread(target=submit, args=(i,)) for i in range(3)
        ]
        for thread in threads:
            thread.start()
        for thread in threads:
            thread.join(timeout=60)
        assert not errors, errors
        assert sorted(done) == [0, 1, 2]
        assert not any(t.is_alive() for t in threads), "deadlocked"
        assert budget.in_flight_bytes == 0
        connector.close()


class TestStoreRtt:
    def test_one_shot_store_stamps_observer(self, tmp_path):
        pool = KVCachePool(POOL_CONFIG)
        connector, _ = make_connector(tmp_path, 0, pool=pool)
        observed = []
        handler = DeviceToStorageHandler(
            pool,
            connector.engine,
            connector.file_mapper,
            rtt_observer=lambda n, io_s, dev_s: observed.append(
                (n, io_s, dev_s)
            ),
        )
        fill_pool_blocks(pool, [0, 1])
        handler.transfer_async(
            1, group_blocks_per_file([0xE], [0, 1], 2)
        )
        assert handler.wait(1) == JobStatus.SUCCEEDED
        assert len(observed) == 1
        nbytes, io_s, dev_s = observed[0]
        assert nbytes == 2 * pool.block_nbytes
        assert io_s > 0
        assert dev_s is not None and dev_s > 0
        connector.close()

    def test_staged_store_stamps_observer(self, tmp_path):
        pool = KVCachePool(POOL_CONFIG)
        connector, _ = make_connector(tmp_path, 0, pool=pool)
        staging = StagingEngine(
            pool, connector.engine, connector.file_mapper, 2,
            StagingConfig(lanes_per_chip=1),
        )
        observed = []
        handler = DeviceToStorageHandler(
            pool,
            connector.engine,
            connector.file_mapper,
            staging=staging,
            rtt_observer=lambda n, io_s, dev_s: observed.append(
                (n, io_s, dev_s)
            ),
        )
        fill_pool_blocks(pool, [0, 1, 2, 3])
        handler.transfer_async(
            1, group_blocks_per_file([0xA, 0xB], [0, 1, 2, 3], 2)
        )
        assert handler.wait(1) == JobStatus.SUCCEEDED
        assert len(observed) == 1
        nbytes, io_s, dev_s = observed[0]
        assert nbytes == 4 * pool.block_nbytes
        assert io_s > 0
        assert dev_s is not None and dev_s > 0
        connector.close()

    def test_advisor_store_estimator_fed(self):
        from llm_d_kv_cache_manager_tpu.tiering.advisor import (
            AdvisorConfig,
            ComputeOrLoadAdvisor,
        )

        advisor = ComputeOrLoadAdvisor(AdvisorConfig())
        assert advisor.estimate_store_s(1 << 20) is None
        advisor.observe_store(1 << 20, 0.1, 0.02)
        stats = advisor.stats()
        assert stats["rtt_store"]["observations"] == 1
        assert stats["store_device_observations"] == 1
        estimate = advisor.estimate_store_s(1 << 20)
        assert estimate is not None and estimate > 0.1


class TestAtomicity:
    """Satellite: a store killed between tmp-write and rename leaves
    no visible file, and lookup never trusts .tmp leftovers."""

    def test_kill_between_tmp_and_rename_leaves_no_visible_file(
        self, tmp_path, monkeypatch
    ):
        engine = _PythonEngine(n_threads=1)

        def dying_replace(src, dst):
            raise OSError("simulated kill between tmp write and rename")

        monkeypatch.setattr(os, "replace", dying_replace)
        path = str(tmp_path / "aa" / "bb" / "deadbeef.bin")
        buffer = np.arange(64, dtype=np.uint8)
        engine.store(1, [path], [buffer], skip_existing=True)
        assert engine.wait(1) == JobStatus.FAILED
        assert not os.path.exists(path), "torn store became visible"
        # The orphan tmp is allowed to exist (a killed process cannot
        # clean up) — but it must never match the block's real name.
        leftovers = [
            name
            for name in os.listdir(tmp_path / "aa" / "bb")
            if ".tmp." in name
        ]
        assert leftovers, "expected an orphan tmp artifact"
        engine.close()

    def test_lookup_rejects_tmp_leftovers(self, tmp_path):
        connector, pool = make_connector(tmp_path, 0)
        manager = connector.get_manager()
        fill_pool_blocks(pool, [0, 1])
        connector.store_handler.transfer_async(
            1, group_blocks_per_file([0x9], [0, 1], 2)
        )
        assert connector.store_handler.wait(1) == JobStatus.SUCCEEDED
        assert manager.lookup([0x9]) == 1

        # Plant an orphan tmp for a DIFFERENT hash, full-sized: the
        # scheduler must not count it (the real path does not exist).
        real = connector.file_mapper.get_file_name(0x9)
        orphan_dir = os.path.dirname(
            connector.file_mapper.get_file_name(0xBEEF)
        )
        os.makedirs(orphan_dir, exist_ok=True)
        with open(real, "rb") as handle:
            payload = handle.read()
        orphan = os.path.join(
            orphan_dir,
            os.path.basename(
                connector.file_mapper.get_file_name(0xBEEF)
            )
            + ".tmp.12345.67890",
        )
        with open(orphan, "wb") as handle:
            handle.write(payload)
        assert manager.lookup([0xBEEF]) == 0
        assert manager.lookup([0x9, 0xBEEF]) == 1

        # A truncated (torn) file at the REAL path is also rejected by
        # the full-file-size gate.
        torn = connector.file_mapper.get_file_name(0x77)
        os.makedirs(os.path.dirname(torn), exist_ok=True)
        with open(torn, "wb") as handle:
            handle.write(payload[: len(payload) // 2])
        assert manager.lookup([0x77]) == 0
        connector.close()


class TestStagedDemotionTarget:
    def _target(self, tmp_path, events=None):
        pool = KVCachePool(POOL_CONFIG)
        connector, _ = make_connector(tmp_path, 2, pool=pool)
        cache = HostTierCache(1 << 22)
        observed = []
        target = StagedDemotionTarget(
            capacity_bytes=64 * pool.block_nbytes,
            pool=pool,
            file_mapper=connector.file_mapper,
            host_cache=cache,
            event_sink=(
                (lambda evts: events.extend(evts))
                if events is not None
                else None
            ),
            store_rtt_observer=lambda n, io_s, dev_s: observed.append(
                (n, io_s)
            ),
        )
        return target, pool, connector, cache, observed

    def test_demotions_move_real_bytes(self, tmp_path):
        events = []
        target, pool, connector, cache, observed = self._target(
            tmp_path, events
        )
        block_ids = [4, 5]
        fill_pool_blocks(pool, block_ids)
        expected = pool.gather_block_major(block_ids)
        target.register_pool_group(
            0xFACE,
            block_ids=block_ids,
            engine_hashes=[0x300, 0x301],
            token_ids=list(range(16)),
            block_size=8,
            now=time.monotonic() - 600,
        )

        # hbm -> host: the bytes must be readable from the host tier.
        assert target.demote(0xFACE, "host")
        cached = cache.get(0xFACE)
        assert cached is not None
        np.testing.assert_array_equal(cached, expected)
        assert [type(e).__name__ for e in events[:2]] == [
            "BlockStored",
            "BlockRemoved",
        ]
        assert events[0].medium == "host"

        # host -> shared_storage: the file must hold the bytes, the
        # host entry retires, the write cost is observed.
        events.clear()
        assert target.demote(0xFACE, "shared_storage")
        path = connector.file_mapper.get_file_name(0xFACE)
        with open(path, "rb") as handle:
            on_disk = np.frombuffer(
                handle.read(), dtype=expected.dtype
            ).reshape(expected.shape)
        np.testing.assert_array_equal(on_disk, expected)
        assert cache.get(0xFACE) is None
        assert events[0].medium == "shared_storage"
        assert observed and observed[0][0] == expected.nbytes
        assert target.tiers() == {"shared_storage": 1}

        # The demoted file round-trips through the load handler (the
        # destination-tier readback assertion).
        connector.load_handler.transfer_async(
            1, [(0xFACE, [20, 21])]
        )
        assert connector.load_handler.wait(1) == JobStatus.SUCCEEDED
        np.testing.assert_array_equal(
            pool.gather_to_host([20, 21]),
            pool.gather_to_host(block_ids),
        )
        connector.close()

    def test_storage_write_failure_keeps_tier(self, tmp_path, monkeypatch):
        target, pool, connector, cache, _ = self._target(tmp_path)
        block_ids = [1, 2]
        fill_pool_blocks(pool, block_ids)
        target.register_pool_group(
            0xB0B,
            block_ids=block_ids,
            engine_hashes=[0x1],
            token_ids=list(range(16)),
            now=time.monotonic() - 600,
        )
        assert target.demote(0xB0B, "host")

        from llm_d_kv_cache_manager_tpu.tiering import staged_target

        monkeypatch.setattr(
            staged_target, "store_file", lambda *a, **kw: False
        )
        assert not target.demote(0xB0B, "shared_storage")
        # Tier unchanged, bytes still host-resident.
        assert target.tiers() == {"host": 1}
        assert cache.get(0xB0B) is not None
        connector.close()

    def test_demotion_survives_concurrent_connector_polling(
        self, tmp_path
    ):
        """The serving loop polls connector.get_finished while the
        demotion thread moves a group down both rungs — the demotion
        must neither hang nor spuriously fail (harvest-race
        regression: the storage write is harvest-free by design)."""
        target, pool, connector, cache, _ = self._target(tmp_path)
        block_ids = [4, 5]
        fill_pool_blocks(pool, block_ids)
        target.register_pool_group(
            0xCAFE,
            block_ids=block_ids,
            engine_hashes=[0x2],
            token_ids=list(range(16)),
            now=time.monotonic() - 600,
        )
        stop = threading.Event()

        def poll():
            while not stop.is_set():
                connector.get_finished()
                time.sleep(0.001)

        poller = threading.Thread(target=poll)
        poller.start()
        try:
            assert target.demote(0xCAFE, "host")
            assert target.demote(0xCAFE, "shared_storage")
        finally:
            stop.set()
            poller.join(timeout=10)
        assert not poller.is_alive()
        assert os.path.exists(
            connector.file_mapper.get_file_name(0xCAFE)
        )
        connector.close()

    def test_requires_host_cache(self, tmp_path):
        pool = KVCachePool(POOL_CONFIG)
        connector, _ = make_connector(tmp_path, 0, pool=pool)
        with pytest.raises(ValueError):
            StagedDemotionTarget(
                capacity_bytes=1024,
                pool=pool,
                file_mapper=connector.file_mapper,
                host_cache=None,
            )
        connector.close()
