"""No-anonymous-threads inventory (ISSUE 14 satellite;
docs/observability.md "Thread roles").

The sampling profiler attributes wall time by thread name, so every
worker/poller/sweeper thread this codebase spawns must carry a stable
``kvtpu-<role>[-<n>]`` name.  This suite boots the service surface —
indexer + tokenization pool, kvevents pool, resync worker, metrics
beat, SLO engine, TTL-cache sweeper, profiler, timeline, HTTP server
AND its per-connection handler threads — and pins that the inventory
stays fully attributed: a new anonymous thread anywhere in the boot
path fails here by name.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request

from llm_d_kv_cache_manager_tpu.api.http_service import serve
from llm_d_kv_cache_manager_tpu.kvcache.indexer import Indexer, IndexerConfig
from llm_d_kv_cache_manager_tpu.kvevents.pool import Pool, PoolConfig
from llm_d_kv_cache_manager_tpu.kvevents.resync import (
    EmptyInventorySource,
    ResyncManager,
)
from llm_d_kv_cache_manager_tpu.metrics.collector import (
    start_metrics_logging,
)
from llm_d_kv_cache_manager_tpu.obs.profiler import (
    ProfilerConfig,
    SamplingProfiler,
    is_attributed,
)
from llm_d_kv_cache_manager_tpu.obs.slo import SloEngine
from llm_d_kv_cache_manager_tpu.obs.timeline import GaugeTimeline
from llm_d_kv_cache_manager_tpu.utils.ttl_cache import TTLCache


def test_booted_service_spawns_only_named_threads():
    baseline = {thread.ident for thread in threading.enumerate()}
    indexer = Indexer(IndexerConfig())
    indexer.run()
    pool = Pool(
        indexer.kv_block_index,
        indexer.token_processor,
        PoolConfig(concurrency=2),
    )
    pool.start()
    resync = ResyncManager(pool, EmptyInventorySource())
    resync.start()
    stop_beat = start_metrics_logging(3600.0)
    slo = SloEngine()
    slo.start(3600.0)
    ttl: TTLCache = TTLCache(60.0)
    ttl.start_sweeper(3600.0)
    profiler = SamplingProfiler(ProfilerConfig(hz=50))
    profiler.start()
    timeline = GaugeTimeline(window_s=30)
    timeline.register("unit", lambda: 1.0)
    timeline.start()
    server = serve(indexer, host="127.0.0.1", port=0)
    base = f"http://127.0.0.1:{server.server_address[1]}"
    held = None
    try:
        # A couple of real requests exercise the handler path...
        for _ in range(3):
            with urllib.request.urlopen(
                base + "/healthz", timeout=30
            ) as response:
                json.load(response)
        # ...and an INCOMPLETE request pins a handler thread alive
        # (blocked reading the rest of the headers) long enough to
        # enumerate it under its renamed role.
        import socket as socket_module

        host, port = server.server_address[:2]
        held = socket_module.create_connection((host, port), timeout=30)
        held.sendall(b"GET /healthz HTTP/1.1\r\nHost: x\r\n")
        handler_named = False
        deadline = time.time() + 10.0
        offenders = set()
        while time.time() < deadline and not handler_named:
            for thread in threading.enumerate():
                if thread.ident in baseline:
                    continue
                name = thread.name
                if name == "kvtpu-http-handler":
                    handler_named = True
                elif "process_request_thread" in name:
                    # The stock mixin name exists for the microseconds
                    # between spawn and the server's rename — only a
                    # handler that NEVER renames (handler_named stays
                    # False) is a failure.
                    continue
                elif not is_attributed(name):
                    offenders.add(name)
            time.sleep(0.02)
        assert not offenders, (
            f"anonymous threads spawned by the booted service: "
            f"{sorted(offenders)} — every thread must carry a "
            f"kvtpu-<role> name (docs/observability.md)"
        )
        assert handler_named, (
            "no kvtpu-http-handler thread observed while a request "
            "was held open"
        )
        # The expected roles actually showed up (the assertion above
        # would pass vacuously if boot silently spawned nothing).
        names = {
            thread.name
            for thread in threading.enumerate()
            if thread.ident not in baseline
        }
        for expected in (
            "kvtpu-events-0",
            "kvtpu-evplane-resync",
            "kvtpu-metrics-beat",
            "kvtpu-slo-engine",
            "kvtpu-ttl-sweeper",
            "kvtpu-profiler",
            "kvtpu-timeline",
            "kvtpu-http-service",
        ):
            assert expected in names, (expected, sorted(names))
    finally:
        if held is not None:
            held.close()
        server.shutdown()
        timeline.close()
        profiler.close()
        ttl.stop_sweeper()
        slo.close()
        stop_beat.set()
        resync.close()
        pool.shutdown()
        indexer.shutdown()


def test_every_thread_site_in_package_is_named():
    """Static sweep: every ``threading.Thread(`` construction and
    ``ThreadPoolExecutor(`` in the package names its threads — the
    inventory can't regress silently in a module this test doesn't
    boot."""
    import re
    from pathlib import Path

    import llm_d_kv_cache_manager_tpu as pkg

    root = Path(pkg.__file__).parent
    offenders = []
    for path in root.rglob("*.py"):
        text = path.read_text()
        for match in re.finditer(
            r"(threading\.Thread\(|ThreadPoolExecutor\()", text
        ):
            # The name/thread_name_prefix argument must appear within
            # the call's argument span (cheap heuristic: the next 400
            # characters — call sites in this codebase are short).
            window = text[match.start(): match.start() + 400]
            if "name=" not in window and "thread_name_prefix=" not in (
                window
            ):
                line = text[: match.start()].count("\n") + 1
                offenders.append(f"{path.relative_to(root)}:{line}")
    assert not offenders, (
        f"thread constructions without a name: {offenders}"
    )
