"""Predictive tiering: feed, eviction ranking, demotion, advisor.

Covers the tentpole's acceptance properties:

* the PolicyFeed contract: family predictions from the ledger EWMA,
  the hash-chain cluster fallback for families seen once, overdue
  back-off, bounded key map, lock-free snapshots;
* predictive eviction: predicted-next-use x byte-cost ranking in
  ``CostAwareMemoryIndex`` and ``HostTierCache``; ``policy=None`` and
  the LRU escape-hatch policy are bit-identical to the pristine
  pop-LRU-first order (the parity oracle);
* the demotion worker's state machine (hbm -> host -> shared_storage),
  the cold-but-reusable gate, the pressure watermark, and the
  per-cycle move budget;
* the demotion ROUND TRIP, end to end through the kvevents pool (not
  unit-mocked): demote -> medium-tagged BlockStored/BlockRemoved ->
  index tier update -> scorer weight change -> ledger per-tier split;
* the compute-or-load advisor: decision rule, hybrid <= min(pure),
  the advice flip when the RTT estimator inflates, estimator feeds;
* the /debug/tiering endpoint and the /healthz tiering block.
"""

from __future__ import annotations

import json
import random
import time
import urllib.request

import numpy as np

from llm_d_kv_cache_manager_tpu.analytics.ledger import (
    CacheStatsLedger,
    LedgerConfig,
)
from llm_d_kv_cache_manager_tpu.api.http_service import serve
from llm_d_kv_cache_manager_tpu.kvcache.indexer import Indexer, IndexerConfig
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.cost_aware import (
    CostAwareMemoryIndex,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import (
    CostAwareIndexConfig,
    PodEntry,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvevents.events import (
    BlockStored,
    EventBatch,
)
from llm_d_kv_cache_manager_tpu.kvevents.pool import (
    Message,
    Pool,
    PoolConfig,
)
from llm_d_kv_cache_manager_tpu.offload.host_tier import HostTierCache
from llm_d_kv_cache_manager_tpu.tiering import (
    Advice,
    AdvisorConfig,
    ComputeOrLoadAdvisor,
    DemotionConfig,
    DemotionWorker,
    LRU_POLICY,
    PodTierState,
    PolicyEngine,
    PolicyFeed,
    PolicyFeedConfig,
    PredictiveEvictionPolicy,
    RttEstimator,
    TieringConfig,
    pool_event_sink,
)
from llm_d_kv_cache_manager_tpu.tiering.demotion import HBM, HOST
from llm_d_kv_cache_manager_tpu.tokenization.pool import (
    TokenizationPoolConfig,
)
from llm_d_kv_cache_manager_tpu.tokenization.tokenizers import Encoding

MODEL = "tiering-model"
BLOCK_SIZE = 4


class WordTokenizer:
    """Deterministic whitespace tokenizer: 'tN' -> N."""

    def type(self) -> str:
        return "word"

    def encode(self, prompt, model_name, add_special_tokens=True):
        tokens, offsets, pos = [], [], 0
        for word in prompt.split(" "):
            tokens.append(int(word[1:]))
            offsets.append((pos, pos + len(word)))
            pos += len(word) + 1
        return Encoding(tokens, offsets)


def prompt_of(tokens) -> str:
    return " ".join(f"t{t}" for t in tokens)


def make_feed(ledger=None, **kw) -> PolicyFeed:
    return PolicyFeed(ledger=ledger, config=PolicyFeedConfig(**kw))


def seeded_feed(family=0xF00, ewma=2.0, now=100.0, keys=(1, 2, 3)):
    """Feed + ledger with one family whose EWMA is ``ewma`` and whose
    chain keys are ``keys`` (family key last)."""
    ledger = CacheStatsLedger(LedgerConfig(sample_rate=1.0))
    chain = list(keys) + [family]
    ledger.record(family, MODEL, 4, 4, now=now - ewma)
    ledger.record(family, MODEL, 4, 4, now=now)
    feed = make_feed(ledger)
    feed.observe_chain(chain, family, now=now)
    feed.refresh(now)
    return feed, ledger


# ----------------------------- feed ------------------------------------


class TestPolicyFeed:
    def test_family_prediction_from_ledger_ewma(self):
        feed, _ = seeded_feed(ewma=2.0, now=100.0)
        snapshot = feed.snapshot()
        prediction = snapshot.prediction_for_key(2)
        assert prediction is not None
        assert prediction.source == "family"
        assert prediction.predicted_interarrival_s == 2.0
        # Half the rhythm elapsed: next use expected in ~1s.
        assert abs(snapshot.expected_next_use_s(2, 101.0) - 1.0) < 1e-6

    def test_overdue_families_back_off(self):
        feed, _ = seeded_feed(ewma=2.0, now=100.0)
        snapshot = feed.snapshot()
        # 10s past a 2s rhythm: 8s overdue -> expected next use grows
        # with the silence instead of clamping at "imminent".
        assert snapshot.expected_next_use_s(2, 110.0) == 8.0

    def test_cluster_fallback_for_single_shot_family(self):
        """A family seen once has no EWMA; it inherits its coarse
        prefix cluster's rhythm (the HashEvict signal: chained keys
        ARE locality-sensitive hashes of the prefix)."""
        ledger = CacheStatsLedger(LedgerConfig(sample_rate=1.0))
        feed = make_feed(ledger, cluster_blocks=2)
        # Two sibling families share chain keys up to block 2 (same
        # cluster key at index 1), arriving 3s apart.
        ledger.record(0xA, MODEL, 4, 4, now=10.0)
        feed.observe_chain([100, 200, 0xA], 0xA, now=10.0)
        ledger.record(0xB, MODEL, 4, 4, now=13.0)
        feed.observe_chain([100, 200, 0xB], 0xB, now=13.0)
        snapshot = feed.refresh(13.0)
        # Neither family has its own EWMA (each seen once)...
        assert ledger.predicted_interarrival_s(0xA) is None
        # ...but both predict through the cluster's 3s rhythm.
        for family in (0xA, 0xB):
            prediction = snapshot.predictions.get(family)
            assert prediction is not None, hex(family)
            assert prediction.source == "cluster"
            assert prediction.predicted_interarrival_s == 3.0

    def test_family_history_beats_cluster(self):
        feed, _ = seeded_feed(ewma=2.0, now=100.0)
        # live query agrees with the snapshot
        prediction = feed.prediction(0xF00, now=100.0)
        assert prediction.source == "family"
        assert prediction.predicted_interarrival_s == 2.0

    def test_key_map_is_bounded_lru(self):
        feed = make_feed(None, key_map_size=8)
        for i in range(4):
            feed.observe_chain([i * 10, i * 10 + 1], i, now=float(i))
        # 8 keys resident; the next chain evicts the oldest pair.
        feed.observe_chain([900, 901], 99, now=10.0)
        snapshot = feed.refresh(10.0)
        assert len(snapshot.key_family) == 8
        assert snapshot.family_of(0) is None  # oldest evicted
        assert snapshot.family_of(900) == 99

    def test_reobserving_resident_keys_evicts_nothing(self):
        """An at-capacity map re-observing its OWN keys must not evict
        unrelated entries (review finding: room was reserved before
        dedup, silently degrading their predictions to the LRU
        proxy)."""
        feed = make_feed(None, key_map_size=6)
        feed.observe_chain([1, 2, 3], 0xA, now=1.0)
        feed.observe_chain([4, 5, 6], 0xB, now=2.0)
        # Full map; re-observe family A's chain repeatedly.
        for _ in range(3):
            feed.observe_chain([1, 2, 3], 0xA, now=3.0)
        snapshot = feed.refresh(3.0)
        assert len(snapshot.key_family) == 6
        assert snapshot.family_of(4) == 0xB  # untouched survivor

    def test_family_cluster_map_is_bounded(self):
        feed = make_feed(None, max_families=4)
        for i in range(10):
            feed.observe_chain([i * 10, i * 10 + 1], i, now=float(i))
        with feed._lock:
            assert len(feed._family_cluster) == 4
            assert 0 not in feed._family_cluster  # oldest evicted
            assert 9 in feed._family_cluster

    def test_unknown_key_predicts_none(self):
        feed, _ = seeded_feed()
        assert feed.snapshot().expected_next_use_s(0xDEAD, 100.0) is None

    def test_observe_keys_registers_extra_keys(self):
        feed, _ = seeded_feed(family=0xF00, now=100.0)
        feed.observe_keys([0xFEED], 0xF00)
        snapshot = feed.refresh(100.0)
        assert snapshot.family_of(0xFEED) == 0xF00
        assert snapshot.expected_next_use_s(0xFEED, 100.5) is not None

    def test_ledger_bulk_export(self):
        _, ledger = seeded_feed(family=0xF00, ewma=2.0, now=100.0)
        rows = ledger.reuse_predictions()
        assert len(rows) == 1
        family, ewma, last_seen, requests = rows[0]
        assert family == 0xF00 and ewma == 2.0
        assert last_seen == 100.0 and requests == 2


# ------------------------- eviction ranking ------------------------------


class TestPredictiveEvictionPolicy:
    def test_prediction_overrides_recency(self):
        """The LRU-oldest entry returns every 2s; a fresher entry's
        family returns hourly — prediction must evict the fresh one."""
        feed, ledger = seeded_feed(family=0xF00, ewma=2.0, now=100.0)
        ledger.record(0xC01D, MODEL, 4, 4, now=100.0 - 3600.0)
        ledger.record(0xC01D, MODEL, 4, 4, now=100.0)
        feed.observe_chain([7, 8, 0xC01D], 0xC01D, now=100.0)
        feed.refresh(100.0)
        policy = PredictiveEvictionPolicy(feed, backend="test")
        # Candidates LRU-first: key 2 (2s family) oldest, key 7
        # (hourly family) newest; equal cost.
        victim = policy.select_victim([(2, 100), (7, 100)], now=101.0)
        assert victim == 1
        assert policy.predicted_choices == 1

    def test_byte_cost_breaks_ties(self):
        feed, _ = seeded_feed()
        policy = PredictiveEvictionPolicy(feed, backend="test")
        # Both unknown: LRU proxy scales by position, but a 100x
        # byte-cost gap dominates the proxy's 2x position spread.
        victim = policy.select_victim([(50, 10), (51, 1000)], now=0.0)
        assert victim == 1

    def test_all_unknown_degrades_toward_lru(self):
        feed = make_feed(None)
        policy = PredictiveEvictionPolicy(feed, backend="test")
        victim = policy.select_victim([(1, 64), (2, 64), (3, 64)], now=0.0)
        assert victim == 0  # oldest wins on equal cost
        assert policy.fallback_choices == 1

    def test_lru_escape_hatch_always_picks_first(self):
        assert LRU_POLICY.select_victim([(9, 1), (8, 999)]) == 0


def _random_ops(index, rng, n=400):
    """Drive a deterministic random add/evict/lookup mix."""
    for i in range(n):
        op = rng.random()
        key = rng.randrange(64)
        if op < 0.6:
            index.add(
                [key * 7 + 1],
                [key],
                [PodEntry(f"pod-{rng.randrange(4)}", "hbm")],
            )
        elif op < 0.8:
            index.evict(key * 7 + 1, [PodEntry("pod-0", "hbm")])
        else:
            index.lookup([key])


class TestCostAwareEvictionPolicy:
    def _tight_index(self, policy=None) -> CostAwareMemoryIndex:
        return CostAwareMemoryIndex(
            CostAwareIndexConfig(
                max_cost_bytes=2000, eviction_policy=policy
            )
        )

    def test_policy_off_parity_is_bit_identical(self):
        """policy=None and the LRU escape-hatch policy must both
        reproduce the pristine eviction order exactly — the parity
        oracle for the policy plumbing."""
        baseline = self._tight_index(policy=None)
        hatch = self._tight_index(policy=LRU_POLICY)
        _random_ops(baseline, random.Random(42))
        _random_ops(hatch, random.Random(42))
        assert baseline.dump_entries() == hatch.dump_entries()
        assert baseline.resident_cost_bytes == hatch.resident_cost_bytes

    def test_predictive_policy_protects_hot_family(self):
        # Real-clock seed: the index's eviction path stamps its own
        # time.monotonic(), so the fake 100.0 clock would read as a
        # massively overdue family.
        feed, ledger = seeded_feed(
            family=0xF00, ewma=1.0, now=time.monotonic()
        )
        # Key 2 belongs to the 1s-rhythm family; fill the index so
        # eviction must pick between it and unpredicted keys.
        policy = PredictiveEvictionPolicy(
            feed, backend="cost_aware", sample=8
        )
        index = self._tight_index(policy=policy)
        index.add([21], [2], [PodEntry("pod-1", "hbm")])
        for i in range(30):
            index.add([1000 + i], [500 + i], [PodEntry("pod-1", "hbm")])
        # Budget pressure evicted many keys, but never the predicted
        # hot key 2 (its expected next use is imminent).
        assert index.lookup([2]), "hot family key was evicted"

    def test_broken_policy_falls_back_to_lru(self):
        class Broken:
            sample = 4

            def select_victim(self, candidates, now=None):
                raise RuntimeError("boom")

        index = self._tight_index(policy=Broken())
        for i in range(40):
            index.add([1000 + i], [500 + i], [PodEntry("pod-1", "hbm")])
        # Evictions happened (budget held) despite the broken policy.
        assert index.resident_cost_bytes <= 2000


class TestHostTierEvictionPolicy:
    def _group(self, nbytes=256):
        return np.zeros(nbytes, dtype=np.uint8)

    def test_policy_off_parity(self):
        baseline = HostTierCache(max_bytes=1024)
        hatch = HostTierCache(max_bytes=1024, eviction_policy=LRU_POLICY)
        evicted_a, evicted_b = [], []
        baseline._on_evict = evicted_a.append
        hatch._on_evict = evicted_b.append
        for cache, log in ((baseline, evicted_a), (hatch, evicted_b)):
            for i in range(8):
                cache.put(i, self._group())
        assert evicted_a == evicted_b
        assert baseline.stats()["entries"] == hatch.stats()["entries"]

    def test_predictive_policy_keeps_hot_group(self):
        now = time.monotonic()  # real clock: put() stamps its own
        feed, _ = seeded_feed(family=0xF00, ewma=1.0, now=now)
        feed.observe_keys([7], 0xF00)
        feed.refresh(now)
        policy = PredictiveEvictionPolicy(
            feed, backend="host_tier", sample=8
        )
        cache = HostTierCache(max_bytes=1024, eviction_policy=policy)
        cache.put(7, self._group())  # the hot group, inserted FIRST
        for i in range(100, 106):
            cache.put(i, self._group())
        # LRU would have evicted 7 (oldest); prediction keeps it.
        assert cache.contains(7)


# ----------------------------- advisor ----------------------------------


class TestRttEstimator:
    def test_cold_estimator_returns_none(self):
        assert RttEstimator().estimate(1024) is None

    def test_floor_plus_per_byte(self):
        estimator = RttEstimator(floor_s=0.05)
        estimator.observe(1 << 20, 0.05 + 0.1)  # 0.1s for 1MB
        estimate = estimator.estimate(2 << 20)
        assert abs(estimate - (0.05 + 0.2)) < 1e-6

    def test_ignores_nonpositive_samples(self):
        estimator = RttEstimator()
        estimator.observe(0, 1.0)
        estimator.observe(100, 0.0)
        assert estimator.stats()["observations"] == 0


class TestComputeOrLoadAdvisor:
    def _advisor(self, per_byte_s=None, prefill=16384.0, **kw) -> ComputeOrLoadAdvisor:
        advisor = ComputeOrLoadAdvisor(
            AdvisorConfig(
                bytes_per_block=1024,
                block_tokens=16,
                prefill_tokens_per_s=prefill,
                **kw,
            )
        )
        if per_byte_s is not None:
            advisor.observe_load(1 << 20, per_byte_s * (1 << 20))
        return advisor

    def test_no_rtt_means_recompute(self):
        advice = self._advisor().advise(64)
        assert advice.action == "recompute"
        assert advice.reason == "no-rtt-observations"

    def test_no_prefill_rate_means_load(self):
        advisor = self._advisor(per_byte_s=1e-9, prefill=0.0)
        assert advisor.advise(64).action == "load"

    def test_slow_rtt_flips_to_recompute(self):
        """The smoke gate's property at unit level: inflating the RTT
        estimator flips the advice away from load."""
        advisor = self._advisor(per_byte_s=1e-9)
        fast = advisor.advise(512)
        assert fast.action in ("load", "hybrid")
        # Inflate: dominate the EWMA with catastrophic observations.
        for _ in range(20):
            advisor.observe_load(1 << 20, 30.0)
        slow = advisor.advise(512)
        assert slow.action == "recompute"
        assert slow.recompute_s < slow.load_s

    def test_hybrid_never_beats_both_pures_dishonestly(self):
        """hybrid_s = min over k of max(load(k), recompute(n-k)):
        by construction <= both pure arms; the advisor must report a
        split consistent with that."""
        advisor = self._advisor(per_byte_s=3e-6)  # load ~ recompute
        advice = advisor.advise(512)
        assert advice.hybrid_s is not None
        assert advice.hybrid_s <= min(advice.load_s, advice.recompute_s) + 1e-9
        if advice.action == "hybrid":
            assert 0 < advice.load_blocks < 512

    def test_hybrid_disabled(self):
        advisor = self._advisor(per_byte_s=3e-6, hybrid=False)
        advice = advisor.advise(512)
        assert advice.hybrid_s is None
        assert advice.action in ("load", "recompute")

    def test_learned_prefill_rate(self):
        advisor = ComputeOrLoadAdvisor(
            AdvisorConfig(bytes_per_block=1024, block_tokens=16)
        )
        advisor.observe_prefill(8192, 0.5)
        assert abs(advisor.prefill_tokens_per_s - 16384.0) < 1e-6

    def test_advice_serializes(self):
        advice = self._advisor(per_byte_s=1e-9).advise(8)
        view = advice.to_dict()
        assert isinstance(advice, Advice)
        assert view["action"] == advice.action
        assert view["blocks"] == 8


# ----------------------------- demotion ---------------------------------


def _make_state(feed, sink=None, capacity=10_000):
    return PodTierState(
        capacity_bytes=capacity, event_sink=sink, feed=feed
    )


def _register(state, key, tokens, nbytes=1000, family=None, now=None):
    state.register_group(
        key,
        engine_hashes=[key * 10 + i for i in range(2)],
        token_ids=tokens,
        nbytes=nbytes,
        block_size=BLOCK_SIZE,
        family=family,
        now=now,
    )


class TestDemotionWorker:
    def test_state_machine_hbm_host_storage(self):
        events = []
        feed, _ = seeded_feed(family=0xF00, now=100.0)
        state = _make_state(feed, sink=events.append)
        _register(
            state, 1, list(range(8)), family=0xF00,
            now=time.monotonic() - 500,
        )
        worker = DemotionWorker(
            state,
            feed,
            DemotionConfig(
                demote_host_idle_s=0.0, demote_storage_idle_s=0.0
            ),
        )
        assert worker.run_cycle() == 1
        assert state.tiers() == {"host": 1}
        assert worker.run_cycle() == 1
        assert state.tiers() == {"shared_storage": 1}
        # Terminal tier: nothing left to demote.
        assert worker.run_cycle() == 0
        # Each transition published store-then-remove with the right
        # mediums.
        mediums = [
            (batch[0].medium, batch[1].medium) for batch in events
        ]
        assert mediums == [("host", "hbm"), ("shared_storage", "host")]

    def test_cold_but_unpredicted_is_left_alone(self):
        feed, _ = seeded_feed(family=0xF00, now=100.0)
        state = _make_state(feed)
        _register(
            state, 1, list(range(8)), family=None,
            now=time.monotonic() - 500,
        )
        worker = DemotionWorker(
            state, feed, DemotionConfig(demote_host_idle_s=0.0)
        )
        assert worker.run_cycle() == 0
        assert state.tiers() == {"hbm": 1}

    def test_pressure_forces_unpredicted_demotion(self):
        feed, _ = seeded_feed()
        state = _make_state(feed, capacity=1000)
        _register(state, 1, list(range(8)), nbytes=900, family=None)
        worker = DemotionWorker(
            state,
            feed,
            DemotionConfig(
                demote_host_idle_s=1e9, pressure_watermark=0.85
            ),
        )
        assert state.pressure() == 0.9
        assert worker.run_cycle() == 1
        assert state.tiers() == {"host": 1}
        record = worker.stats()["recent"][0]
        assert record["forced_by_pressure"] is True

    def test_move_budget_bounds_a_cycle(self):
        feed, _ = seeded_feed(family=0xF00, now=100.0)
        state = _make_state(feed)
        old = time.monotonic() - 500
        for i in range(10):
            _register(state, i, list(range(8)), family=0xF00, now=old)
        worker = DemotionWorker(
            state,
            feed,
            DemotionConfig(
                demote_host_idle_s=0.0, max_moves_per_cycle=3
            ),
        )
        assert worker.run_cycle() == 3
        assert state.tiers() == {"hbm": 7, "host": 3}

    def test_coldest_reusable_goes_first(self):
        """Ranking: the group whose predicted next use is farthest
        demotes first."""
        ledger = CacheStatsLedger(LedgerConfig(sample_rate=1.0))
        feed = make_feed(ledger)
        now = time.monotonic()
        for family, ewma in ((0xA, 1.0), (0xB, 900.0)):
            ledger.record(family, MODEL, 4, 4, now=now - ewma)
            ledger.record(family, MODEL, 4, 4, now=now)
            feed.observe_chain([family * 100, family], family, now=now)
        state = _make_state(feed)
        _register(state, 1, list(range(8)), family=0xA, now=now - 50)
        _register(state, 2, list(range(8)), family=0xB, now=now - 50)
        worker = DemotionWorker(
            state,
            feed,
            DemotionConfig(
                demote_host_idle_s=0.0, max_moves_per_cycle=1
            ),
        )
        assert worker.run_cycle() == 1
        tiers = {
            key: group.tier for key, group in state._groups.items()
        }
        assert tiers[2] == "host"  # the ~15-minute family demoted
        assert tiers[1] == "hbm"  # the 1s family stayed put

    def test_worker_start_close_idempotent(self):
        feed, _ = seeded_feed()
        worker = DemotionWorker(
            _make_state(feed), feed, DemotionConfig(interval_s=0.05)
        )
        worker.start()
        worker.start()
        time.sleep(0.12)
        worker.close()
        worker.close()
        assert worker.stats()["cycles"] >= 1
        assert worker.stats()["running"] is False

    def test_host_cache_rejection_keeps_tier(self):
        class RejectingCache:
            def put(self, key, group):
                return False

        feed, _ = seeded_feed(family=0xF00, now=100.0)
        state = PodTierState(
            capacity_bytes=10_000,
            host_cache=RejectingCache(),
            feed=feed,
        )
        _register(
            state, 1, list(range(8)), family=0xF00,
            now=time.monotonic() - 500,
        )
        assert state.demote(1, HOST) is False
        assert state.tiers() == {HBM: 1}


# ------------------- demotion round trip (e2e) --------------------------


class TestDemotionRoundTrip:
    """Satellite: demote a block group -> medium-tagged events through
    the REAL kvevents pool -> index tier update -> scorer weight change
    -> ledger per-tier hit split.  Nothing mocked below the sink."""

    def _stack(self):
        ledger = CacheStatsLedger(
            LedgerConfig(sample_rate=1.0, tier_sample=1)
        )
        indexer = Indexer(
            IndexerConfig(
                token_processor_config=TokenProcessorConfig(
                    block_size=BLOCK_SIZE
                ),
                tokenizers_pool_config=TokenizationPoolConfig(
                    workers=1, model_name=MODEL
                ),
            ),
            tokenizer=WordTokenizer(),
            cache_stats_ledger=ledger,
        )
        indexer.run()
        pool = Pool(
            indexer.kv_block_index,
            indexer.token_processor,
            PoolConfig(concurrency=2),
        )
        pool.start()
        return indexer, pool, ledger

    def test_round_trip(self):
        indexer, pool, ledger = self._stack()
        try:
            engine = PolicyEngine(
                ledger=ledger, config=TieringConfig()
            )
            indexer.set_policy_engine(engine)
            tokens = list(range(1, 33))  # 8 blocks of 4
            n_blocks = len(tokens) // BLOCK_SIZE
            prompt = prompt_of(tokens)
            engine_hashes = [0x9000 + i for i in range(n_blocks)]

            # Seed the chain on pod-1 at hbm through the pool, as the
            # engine's publisher would.
            batch = EventBatch(
                ts=1.0,
                events=[
                    BlockStored(
                        block_hashes=list(engine_hashes),
                        parent_block_hash=None,
                        token_ids=tokens,
                        block_size=BLOCK_SIZE,
                        medium="hbm",
                    )
                ],
            )
            pool.add_task(
                Message(
                    topic=f"kv@pod-1@{MODEL}",
                    payload=batch.encode(),
                    pod_identifier="pod-1",
                    model_name=MODEL,
                )
            )
            pool.drain()

            # Scored at hbm: full-weight chain, hbm tier split.
            scores = indexer.get_pod_scores(prompt, MODEL, ["pod-1"])
            assert scores["pod-1"] == float(n_blocks)
            ledger.flush_metrics()
            assert ledger.snapshot()["totals"]["tiers"] == {
                "hbm": n_blocks
            }

            # Demote the whole group hbm -> host through the worker;
            # its events ride the SAME pool path as live traffic.
            state = PodTierState(
                capacity_bytes=10_000,
                event_sink=pool_event_sink(pool, "pod-1", MODEL),
                feed=engine.feed,
            )
            family = ledger.family_key(
                indexer.token_processor.tokens_to_kv_block_keys(
                    0, tokens, MODEL
                ),
                n_blocks,
            )
            state.register_group(
                0xF11E,
                engine_hashes=engine_hashes,
                token_ids=tokens,
                nbytes=4096,
                block_size=BLOCK_SIZE,
                family=family,
                now=time.monotonic() - 600,
            )
            worker = engine.start_demotion(
                state,
                DemotionConfig(
                    demote_host_idle_s=0.0, require_prediction=False
                ),
                start=False,
            )
            assert worker.run_cycle() == 1
            pool.drain()

            # Index tier updated: the chain is now host-resident only.
            request_keys = indexer.token_processor.tokens_to_kv_block_keys(
                0, tokens, MODEL
            )
            found = indexer.kv_block_index.lookup(request_keys)
            tiers = {
                entry.device_tier
                for pods in found.values()
                for entry in pods
            }
            assert tiers == {"host"}

            # Scorer weight change: host weighs 0.8 per block.
            scores = indexer.get_pod_scores(prompt, MODEL, ["pod-1"])
            assert abs(scores["pod-1"] - 0.8 * n_blocks) < 1e-9

            # Ledger per-tier split reflects the demotion.
            ledger.flush_metrics()
            tiers_total = ledger.snapshot()["totals"]["tiers"]
            assert tiers_total.get("host") == n_blocks, tiers_total
            engine.close()
        finally:
            pool.shutdown()
            indexer.shutdown()


# ------------------------ engine + debug surface -------------------------


class TestPolicyEngineSurface:
    def test_observe_scored_populates_feed(self):
        ledger = CacheStatsLedger(LedgerConfig(sample_rate=1.0))
        engine = PolicyEngine(
            ledger=ledger,
            config=TieringConfig(refresh_s=0.0),
        )
        ledger.record(0xAB, MODEL, 4, 4)
        engine.observe_scored([1, 2, 0xAB], 0xAB)
        status = engine.status()
        assert status["feed"]["observed_chains"] == 1
        assert status["feed"]["keys_mapped"] == 3

    def test_debug_endpoint_and_healthz(self):
        indexer = Indexer(
            IndexerConfig(
                token_processor_config=TokenProcessorConfig(
                    block_size=BLOCK_SIZE
                ),
                tokenizers_pool_config=TokenizationPoolConfig(
                    workers=1, model_name=MODEL
                ),
            ),
            tokenizer=WordTokenizer(),
        )
        indexer.run()
        engine = PolicyEngine(ledger=indexer.cache_stats)
        indexer.set_policy_engine(engine)
        server = serve(
            indexer, host="127.0.0.1", port=0, tiering=engine
        )
        base = f"http://127.0.0.1:{server.server_address[1]}"
        try:
            with urllib.request.urlopen(
                base + "/debug/tiering", timeout=10
            ) as response:
                payload = json.load(response)
            assert "feed" in payload and "advisor" in payload
            assert payload["config"]["eviction_sample"] >= 1
            with urllib.request.urlopen(
                base + "/healthz", timeout=10
            ) as response:
                health = json.load(response)
            assert "tiering" in health
            assert "advice_counts" in health["tiering"]
        finally:
            server.shutdown()
            engine.close()
            indexer.shutdown()

    def test_debug_endpoint_404_when_disabled(self):
        indexer = Indexer(
            IndexerConfig(
                token_processor_config=TokenProcessorConfig(
                    block_size=BLOCK_SIZE
                ),
                tokenizers_pool_config=TokenizationPoolConfig(
                    workers=1, model_name=MODEL
                ),
            ),
            tokenizer=WordTokenizer(),
        )
        indexer.run()
        server = serve(indexer, host="127.0.0.1", port=0)
        base = f"http://127.0.0.1:{server.server_address[1]}"
        try:
            try:
                urllib.request.urlopen(base + "/debug/tiering", timeout=10)
                raise AssertionError("expected 404")
            except urllib.error.HTTPError as exc:
                assert exc.code == 404
        finally:
            server.shutdown()
            indexer.shutdown()

    def test_explain_carries_advice(self):
        indexer = Indexer(
            IndexerConfig(
                token_processor_config=TokenProcessorConfig(
                    block_size=BLOCK_SIZE
                ),
                tokenizers_pool_config=TokenizationPoolConfig(
                    workers=1, model_name=MODEL
                ),
            ),
            tokenizer=WordTokenizer(),
        )
        indexer.run()
        try:
            engine = PolicyEngine(ledger=indexer.cache_stats)
            engine.advisor.config.bytes_per_block = 1024
            engine.advisor.observe_load(1 << 20, 0.01)
            engine.advisor.observe_prefill(8192, 0.5)
            indexer.set_policy_engine(engine)
            tokens = list(range(1, 17))
            keys = indexer.token_processor.tokens_to_kv_block_keys(
                0, tokens, MODEL
            )
            indexer.kv_block_index.add(
                keys, keys, [PodEntry("pod-1", "host")]
            )
            _, explanation = indexer.get_pod_scores_explained(
                prompt_of(tokens), MODEL
            )
            advice = explanation.get("tiering")
            assert advice is not None
            assert advice["pod"] == "pod-1"
            assert advice["blocks"] == len(keys)
            assert advice["action"] in ("load", "recompute", "hybrid")
            engine.close()
        finally:
            indexer.shutdown()
