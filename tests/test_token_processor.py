"""Hash-chain parity tests.

The chain must agree bit-for-bit with the reference indexer
(pkg/kvcache/kvblock/token_processor.go) — golden vectors here are
hand-computed from the published algorithm (FNV-64a over RFC 8949 canonical
CBOR) with independent encodings written out byte by byte, so a bug in the
production encoder cannot hide in the test.
"""

import pytest

from llm_d_kv_cache_manager_tpu.kvcache.kvblock.cbor_canonical import (
    encode_canonical,
    encode_hash_payload,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    EMPTY_BLOCK_HASH,
    ChunkedTokenDatabase,
    TokenProcessorConfig,
    engine_hash_to_uint64,
    fnv1a_64,
)

# Published FNV-1a 64-bit test vectors.
FNV_VECTORS = {
    b"": 0xCBF29CE484222325,
    b"a": 0xAF63DC4C8601EC8C,
    b"foobar": 0x85944171F73967E8,
}


def test_fnv1a_64_known_vectors():
    for data, expected in FNV_VECTORS.items():
        assert fnv1a_64(data) == expected


class TestCanonicalCbor:
    def test_uint_boundaries(self):
        # Shortest-form heads at every width boundary (RFC 8949 §4.2.1).
        assert encode_canonical(0) == bytes([0x00])
        assert encode_canonical(23) == bytes([0x17])
        assert encode_canonical(24) == bytes([0x18, 24])
        assert encode_canonical(255) == bytes([0x18, 0xFF])
        assert encode_canonical(256) == bytes([0x19, 0x01, 0x00])
        assert encode_canonical(65536) == bytes([0x1A, 0x00, 0x01, 0x00, 0x00])
        assert encode_canonical(2**32) == bytes(
            [0x1B, 0, 0, 0, 1, 0, 0, 0, 0]
        )
        assert encode_canonical(2**64 - 1) == bytes([0x1B] + [0xFF] * 8)

    def test_text_null_array(self):
        assert encode_canonical(None) == bytes([0xF6])
        assert encode_canonical("m") == bytes([0x61, 0x6D])
        assert encode_canonical([1, 2]) == bytes([0x82, 0x01, 0x02])

    def test_hash_payload_layout(self):
        # [parent=5, tokens=[1, 300], extra=None], hand-encoded.
        expected = bytes(
            [0x83, 0x05, 0x82, 0x01, 0x19, 0x01, 0x2C, 0xF6]
        )
        assert encode_hash_payload(5, [1, 300], None) == expected

    def test_hash_payload_nil_tokens_and_model(self):
        # [parent=0xCBF29CE484222325, tokens=null, extra="m"]
        expected = (
            bytes([0x83, 0x1B])
            + (0xCBF29CE484222325).to_bytes(8, "big")
            + bytes([0xF6, 0x61, 0x6D])
        )
        assert encode_hash_payload(0xCBF29CE484222325, None, "m") == expected


class TestChunkedTokenDatabase:
    def test_golden_chain_empty_seed(self):
        """Fully hand-derived two-block chain for seed=""."""
        db = ChunkedTokenDatabase(TokenProcessorConfig(block_size=2))
        init = fnv1a_64(b"")  # seed "" -> FNV offset basis
        model_init = fnv1a_64(encode_hash_payload(init, None, "m"))
        h1 = fnv1a_64(encode_hash_payload(model_init, [1, 2], None))
        h2 = fnv1a_64(encode_hash_payload(h1, [3, 4], None))
        assert db.tokens_to_kv_block_keys(
            EMPTY_BLOCK_HASH, [1, 2, 3, 4], "m"
        ) == [h1, h2]

    def test_deterministic_across_instances(self):
        cfg = TokenProcessorConfig(block_size=4, hash_seed="42")
        tokens = list(range(20))
        a = ChunkedTokenDatabase(cfg).tokens_to_kv_block_keys(
            EMPTY_BLOCK_HASH, tokens, "model-x"
        )
        b = ChunkedTokenDatabase(cfg).tokens_to_kv_block_keys(
            EMPTY_BLOCK_HASH, tokens, "model-x"
        )
        assert a == b
        assert len(a) == 5

    def test_no_partial_blocks(self):
        db = ChunkedTokenDatabase(TokenProcessorConfig(block_size=16))
        assert db.tokens_to_kv_block_keys(EMPTY_BLOCK_HASH, [1] * 15, "m") == []
        assert (
            len(db.tokens_to_kv_block_keys(EMPTY_BLOCK_HASH, [1] * 47, "m"))
            == 2
        )

    def test_seed_and_model_change_hashes(self):
        tokens = list(range(16))
        base = ChunkedTokenDatabase(
            TokenProcessorConfig(hash_seed="")
        ).tokens_to_kv_block_keys(EMPTY_BLOCK_HASH, tokens, "m1")
        seeded = ChunkedTokenDatabase(
            TokenProcessorConfig(hash_seed="7")
        ).tokens_to_kv_block_keys(EMPTY_BLOCK_HASH, tokens, "m1")
        other_model = ChunkedTokenDatabase(
            TokenProcessorConfig(hash_seed="")
        ).tokens_to_kv_block_keys(EMPTY_BLOCK_HASH, tokens, "m2")
        assert base != seeded
        assert base != other_model

    def test_parent_chain_continuation(self):
        """Keys for [A|B] computed at once equal keys for A then B chained
        off A's last key — the event write path depends on this."""
        db = ChunkedTokenDatabase(TokenProcessorConfig(block_size=4))
        tokens = list(range(16))
        whole = db.tokens_to_kv_block_keys(EMPTY_BLOCK_HASH, tokens, "m")
        head = db.tokens_to_kv_block_keys(EMPTY_BLOCK_HASH, tokens[:8], "m")
        tail = db.tokens_to_kv_block_keys(head[-1], tokens[8:], "m")
        assert head + tail == whole

    def test_block_size_boundary_exact(self):
        db = ChunkedTokenDatabase(TokenProcessorConfig(block_size=4))
        keys = db.tokens_to_kv_block_keys(EMPTY_BLOCK_HASH, list(range(8)), "m")
        assert len(keys) == 2
        assert len(set(keys)) == 2


class TestEngineHashNormalization:
    def test_int_passthrough(self):
        assert engine_hash_to_uint64(42) == 42
        # Negative int64 wire values map to their uint64 bit pattern.
        assert engine_hash_to_uint64(-1) == 0xFFFFFFFFFFFFFFFF

    def test_bytes_last8_big_endian(self):
        digest = bytes(range(32))  # e.g. a sha256_cbor digest
        assert engine_hash_to_uint64(digest) == int.from_bytes(
            digest[-8:], "big"
        )

    def test_short_bytes_zero_padded(self):
        assert engine_hash_to_uint64(b"\x01\x02") == 0x0102

    def test_invalid_inputs(self):
        with pytest.raises(ValueError):
            engine_hash_to_uint64(b"")
        with pytest.raises(TypeError):
            engine_hash_to_uint64("nope")
        with pytest.raises(TypeError):
            engine_hash_to_uint64(True)
