import pytest

from llm_d_kv_cache_manager_tpu.preprocessing.chat_templating import (
    ApplyChatTemplateRequest,
    ChatTemplatingProcessor,
)
from llm_d_kv_cache_manager_tpu.tokenization.pool import (
    TokenizationPool,
    TokenizationPoolConfig,
)
from llm_d_kv_cache_manager_tpu.tokenization.prefixstore.lru_store import (
    LRUStoreConfig,
    LRUTokenStore,
)
from llm_d_kv_cache_manager_tpu.tokenization.prefixstore.trie_store import (
    TrieTokenStore,
)
from llm_d_kv_cache_manager_tpu.tokenization.tokenizers import (
    CompositeTokenizer,
    LocalFastTokenizer,
    char_offsets_to_byte_offsets,
)
from tests.helpers.tiny_tokenizer import (
    build_transformers_tokenizer,
    save_tokenizer_json,
)


def test_char_to_byte_offsets_ascii_identity():
    text = "hello world"
    offsets = [(0, 5), (6, 11)]
    assert char_offsets_to_byte_offsets(text, offsets) == offsets


def test_char_to_byte_offsets_multibyte():
    text = "héllo"  # é is 2 bytes
    assert char_offsets_to_byte_offsets(text, [(0, 5)]) == [(0, 6)]
    assert char_offsets_to_byte_offsets(text, [(2, 5)]) == [(3, 6)]


class TestPrefixStores:
    def make_tokenization(self, n_words=200):
        words = [f"w{i:04d}" for i in range(n_words)]
        prompt = " ".join(words)
        tokens, offsets, pos = [], [], 0
        for i, word in enumerate(words):
            tokens.append(i)
            offsets.append((pos, pos + len(word)))
            pos += len(word) + 1
        return prompt, tokens, offsets

    @pytest.mark.parametrize("store_cls", ["lru", "trie"])
    def test_full_prefix_roundtrip(self, store_cls):
        prompt, tokens, offsets = self.make_tokenization()
        store = (
            LRUTokenStore(LRUStoreConfig(block_size=64))
            if store_cls == "lru"
            else TrieTokenStore()
        )
        store.add_tokenization(prompt, tokens, offsets)
        found, ratio = store.find_longest_contained_tokens(prompt)
        assert ratio > 0.9
        assert found == tokens[: len(found)]
        assert len(found) > 0.8 * len(tokens)

    def test_lru_partial_prefix(self):
        prompt, tokens, offsets = self.make_tokenization()
        store = LRUTokenStore(LRUStoreConfig(block_size=64))
        store.add_tokenization(prompt, tokens, offsets)
        # A prompt sharing only the first half: coverage reflects the split.
        half = prompt[: len(prompt) // 2] + " entirely different tail " * 20
        found, ratio = store.find_longest_contained_tokens(half)
        assert 0.0 < ratio < 0.6
        assert found == tokens[: len(found)]

    def test_lru_unknown_prompt_zero(self):
        store = LRUTokenStore(LRUStoreConfig(block_size=64))
        found, ratio = store.find_longest_contained_tokens("never seen " * 50)
        assert found == [] and ratio == 0.0

    def test_lru_rejects_mismatched_lengths(self):
        store = LRUTokenStore()
        with pytest.raises(ValueError):
            store.add_tokenization("abc", [1, 2], [(0, 1)])

    def test_lru_ignores_empty(self):
        store = LRUTokenStore()
        store.add_tokenization("", [], [])
        store.add_tokenization("abc", [], [])

    @pytest.mark.parametrize("store_cls", ["lru", "trie"])
    def test_models_never_alias(self, store_cls):
        """Tokens cached for model A must not serve model B's lookups."""
        prompt, tokens, offsets = self.make_tokenization()
        store = (
            LRUTokenStore(LRUStoreConfig(block_size=64))
            if store_cls == "lru"
            else TrieTokenStore()
        )
        store.add_tokenization(prompt, tokens, offsets, "model-a")
        found_b, ratio_b = store.find_longest_contained_tokens(
            prompt, "model-b"
        )
        assert found_b == [] and ratio_b == 0.0
        found_a, ratio_a = store.find_longest_contained_tokens(
            prompt, "model-a"
        )
        assert ratio_a > 0.9 and found_a


@pytest.fixture(scope="module")
def local_tokenizer_dir(tmp_path_factory):
    directory = tmp_path_factory.mktemp("tokenizers")
    return save_tokenizer_json(str(directory), "test-model")


class TestLocalFastTokenizer:
    def test_encode_with_byte_offsets(self, local_tokenizer_dir):
        tokenizer = LocalFastTokenizer(local_tokenizer_dir)
        encoding = tokenizer.encode(
            "the quick brown fox", "test-model", add_special_tokens=True
        )
        assert len(encoding.tokens) == 4
        assert encoding.offsets[0] == (0, 3)
        assert encoding.offsets[1] == (4, 9)

    def test_missing_model_raises(self, local_tokenizer_dir):
        tokenizer = LocalFastTokenizer(local_tokenizer_dir)
        with pytest.raises(FileNotFoundError):
            tokenizer.encode("x", "no-such-model", True)

    def test_composite_fallback(self, local_tokenizer_dir):
        class Broken:
            def type(self):
                return "broken"

            def encode(self, *a):
                raise RuntimeError("boom")

        composite = CompositeTokenizer(
            [Broken(), LocalFastTokenizer(local_tokenizer_dir)]
        )
        encoding = composite.encode("lazy dog", "test-model", True)
        assert len(encoding.tokens) == 2

    def test_composite_all_fail(self):
        class Broken:
            def type(self):
                return "broken"

            def encode(self, *a):
                raise RuntimeError("boom")

        with pytest.raises(RuntimeError, match="all tokenizer backends"):
            CompositeTokenizer([Broken()]).encode("x", "m", True)


class CountingTokenizer:
    """Wraps LocalFastTokenizer counting full-encode calls."""

    def __init__(self, inner):
        self.inner = inner
        self.calls = 0

    def type(self):
        return "counting"

    def encode(self, prompt, model, add_special_tokens):
        self.calls += 1
        return self.inner.encode(prompt, model, add_special_tokens)


class TestTokenizationPool:
    def test_sync_tokenize_and_fast_path(self, local_tokenizer_dir):
        counting = CountingTokenizer(LocalFastTokenizer(local_tokenizer_dir))
        store = LRUTokenStore(LRUStoreConfig(block_size=16))
        pool = TokenizationPool(
            counting,
            store,
            TokenizationPoolConfig(workers=2, model_name="test-model"),
        )
        prompt = "the quick brown fox jumps over the lazy dog . " * 10
        first = pool.tokenize(prompt)
        assert counting.calls == 1
        assert len(first) > 50
        # Same prompt again: prefix store coverage >= 0.8, no new encode.
        second = pool.tokenize(prompt)
        assert counting.calls == 1
        assert second == first[: len(second)]
        pool.shutdown()

    def test_async_enqueue_warms_store(self, local_tokenizer_dir):
        counting = CountingTokenizer(LocalFastTokenizer(local_tokenizer_dir))
        store = LRUTokenStore(LRUStoreConfig(block_size=16))
        pool = TokenizationPool(
            counting,
            store,
            TokenizationPoolConfig(workers=1, model_name="test-model"),
        )
        prompt = "pack my box with five dozen liquor jugs . " * 8
        pool.enqueue_tokenization(prompt)
        pool._queue.join()
        found, ratio = store.find_longest_contained_tokens(
            prompt, "test-model"
        )
        assert ratio >= 0.8
        pool.shutdown()

    def test_sync_miss_probes_store_exactly_once(
        self, local_tokenizer_dir
    ):
        """The caller thread probes the prefix store before queueing;
        a miss carries ``store_probed`` on the task so the worker does
        NOT pay a second probe for the same prompt (one store read per
        miss, not two)."""

        class CountingStore(LRUTokenStore):
            def __init__(self, *a, **kw):
                super().__init__(*a, **kw)
                self.probes = 0

            def probe(self, prompt, model, key_space=None):
                self.probes += 1
                return super().probe(prompt, model, key_space)

        store = CountingStore(LRUStoreConfig(block_size=16))
        pool = TokenizationPool(
            CountingTokenizer(LocalFastTokenizer(local_tokenizer_dir)),
            store,
            TokenizationPoolConfig(workers=1, model_name="test-model"),
        )
        prompt = "sphinx of black quartz judge my vow . " * 8
        pool.tokenize(prompt)  # cold miss
        assert store.probes == 1
        # A warm repeat is served by the caller-side probe alone.
        pool.tokenize(prompt)
        assert store.probes == 2
        # Fire-and-forget tasks were never pre-probed: the worker-side
        # probe must still run for them (probe + hit, no new encode).
        pool.enqueue_tokenization(prompt)
        pool._queue.join()
        assert store.probes == 3
        pool.shutdown()

    def test_retries_then_fails(self):
        class AlwaysBroken:
            def type(self):
                return "broken"

            def encode(self, *a):
                raise RuntimeError("flaky")

        pool = TokenizationPool(
            AlwaysBroken(),
            LRUTokenStore(),
            TokenizationPoolConfig(workers=1, max_retries=3, model_name="m"),
        )
        with pytest.raises(RuntimeError, match="flaky"):
            pool.tokenize("some prompt")
        pool.shutdown()


class TestChatTemplating:
    def test_render_and_tokenize_without_specials(self, local_tokenizer_dir):
        processor = ChatTemplatingProcessor()
        processor.register_tokenizer(
            "test-model", build_transformers_tokenizer()
        )
        rendered = processor.apply_chat_template(
            "test-model",
            ApplyChatTemplateRequest(
                conversation=[
                    {"role": "system", "content": "you are a helpful assistant ."},
                    {"role": "user", "content": "hello world"},
                ]
            ),
        )
        assert rendered.startswith("<|system|>")
        assert rendered.rstrip().endswith("<|assistant|>")

        pool = TokenizationPool(
            LocalFastTokenizer(local_tokenizer_dir),
            LRUTokenStore(LRUStoreConfig(block_size=16)),
            TokenizationPoolConfig(workers=1, model_name="test-model"),
            chat_processor=processor,
        )
        tokens = pool.tokenize(
            "",
            render_req=ApplyChatTemplateRequest(
                conversation=[{"role": "user", "content": "hello world"}]
            ),
        )
        assert len(tokens) >= 3  # <|user|> hello world <|assistant|>
        pool.shutdown()

    def test_explicit_template_override(self):
        processor = ChatTemplatingProcessor()
        processor.register_tokenizer(
            "test-model", build_transformers_tokenizer()
        )
        rendered = processor.apply_chat_template(
            "test-model",
            ApplyChatTemplateRequest(
                conversation=[{"role": "user", "content": "hi"}],
                chat_template="{{ messages[0]['content'] }}!",
                add_generation_prompt=False,
            ),
        )
        assert rendered == "hi!"
