"""KV-transfer planning plane: planner, executor, warm-up, blending.

Covers the tentpole's acceptance properties:

* the planner decision table: every outcome label, the pricing rule
  (transfer beats recompute by the margin or no plan), the zero-RTT
  edge (no measurements -> recompute, never plan on a guess), plan
  determinism under a fixed feed snapshot;
* the executor's safety properties, end to end through the kvevents
  pool (not unit-mocked): a copied plan flips the target pod's score
  through real BlockStored events; a source that died mid-plan
  invalidates the plan and publishes NOTHING (no phantom index
  entries); the transfer-vs-demotion race (executor removes from the
  tier the source holds NOW, not the tier the plan remembered);
* instant-warm scale-out: hot-family catalog, cold-pod registration,
  the budgeted drain, ledger-ranked family selection;
* load-blended scoring: the LOAD_BLEND fold, bit-identical parity
  when off, the explain surface;
* the unknown-pod filter fix-up: filtered-but-absent pods get
  explicit zero entries in the straight lane, the fast lane, and the
  explained walk, so the planner/ledger/explain candidate sets agree;
* the /debug/transfer endpoint, the /healthz transfer block, and the
  planned scoring variant riding POST /score_completions.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request

from llm_d_kv_cache_manager_tpu.api.http_service import serve
from llm_d_kv_cache_manager_tpu.analytics.ledger import (
    CacheStatsLedger,
    LedgerConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.indexer import Indexer, IndexerConfig
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvevents.events import (
    BlockRemoved,
    BlockStored,
    EventBatch,
)
from llm_d_kv_cache_manager_tpu.kvevents.pool import (
    Message,
    Pool,
    PoolConfig,
)
from llm_d_kv_cache_manager_tpu.tiering import (
    AdvisorConfig,
    ComputeOrLoadAdvisor,
)
from llm_d_kv_cache_manager_tpu.tokenization.pool import (
    TokenizationPoolConfig,
)
from llm_d_kv_cache_manager_tpu.tokenization.tokenizers import Encoding
from llm_d_kv_cache_manager_tpu.transfer import (
    DONE,
    EXPIRED,
    INVALIDATED,
    HotFamilyCatalog,
    TransferConfig,
    TransferEngine,
    TransferExecutor,
    TransferPlanner,
    WarmupWorker,
)

MODEL = "transfer-model"
BLOCK_SIZE = 4


class WordTokenizer:
    """Deterministic whitespace tokenizer: 'tN' -> N."""

    def type(self) -> str:
        return "word"

    def encode(self, prompt, model_name, add_special_tokens=True):
        tokens, offsets, pos = [], [], 0
        for word in prompt.split(" "):
            tokens.append(int(word[1:]))
            offsets.append((pos, pos + len(word)))
            pos += len(word) + 1
        return Encoding(tokens, offsets)


def prompt_of(tokens) -> str:
    return " ".join(f"t{t}" for t in tokens)


def fed_advisor(
    bytes_per_block=1024, prefill_rate=50.0, load_s=0.001, store_s=0.0005
):
    """Advisor with both RTT models fed: transfers price cheap."""
    advisor = ComputeOrLoadAdvisor(
        AdvisorConfig(
            bytes_per_block=bytes_per_block,
            block_tokens=BLOCK_SIZE,
            prefill_tokens_per_s=prefill_rate,
        )
    )
    if load_s is not None:
        advisor.observe_load(4096, load_s)
    if store_s is not None:
        advisor.observe_store(4096, store_s)
    return advisor


def prov(score, blocks, tiers=None):
    """One pod's scorer-explain provenance entry."""
    return {
        "score": score,
        "blocks_matched": blocks,
        "break_index": blocks,
        "tiers": dict(tiers) if tiers else {"hbm": blocks},
    }


def make_planner(advisor=None, **kw):
    kw.setdefault("load_threshold", 2.0)
    return TransferPlanner(advisor or fed_advisor(), **kw)


def make_stack(ledger=None, **config_kw):
    indexer = Indexer(
        IndexerConfig(
            token_processor_config=TokenProcessorConfig(
                block_size=BLOCK_SIZE
            ),
            tokenizers_pool_config=TokenizationPoolConfig(
                workers=1, model_name=MODEL
            ),
            **config_kw,
        ),
        tokenizer=WordTokenizer(),
        cache_stats_ledger=ledger,
    )
    indexer.run()
    pool = Pool(
        indexer.kv_block_index,
        indexer.token_processor,
        PoolConfig(concurrency=2),
    )
    pool.start()
    return indexer, pool


def publish(pool, pod, events):
    pool.add_task(
        Message(
            topic=f"kv@{pod}@{MODEL}",
            payload=EventBatch(ts=1.0, events=events).encode(),
            pod_identifier=pod,
            model_name=MODEL,
        )
    )
    pool.drain()


def seed_chain(pool, pod, engine_hashes, tokens, medium="hbm"):
    publish(
        pool,
        pod,
        [
            BlockStored(
                block_hashes=list(engine_hashes),
                parent_block_hash=None,
                token_ids=list(tokens),
                block_size=BLOCK_SIZE,
                medium=medium,
            )
        ],
    )


# ----------------------------- planner ----------------------------------


class TestPlannerDecision:
    KEYS = [11, 22, 33, 44]

    def test_planned_happy_path(self):
        planner = make_planner()
        plan, outcome = planner.plan(
            {"pod-1": prov(4.0, 4), "pod-2": prov(0.0, 0)},
            {"pod-1": 5.0, "pod-2": 0.0},
            self.KEYS,
            token_ids=list(range(16)),
            block_size=BLOCK_SIZE,
        )
        assert outcome == "planned"
        assert plan.source_pod == "pod-1"
        assert plan.target_pod == "pod-2"
        assert plan.blocks == 4
        assert plan.block_keys == self.KEYS
        assert plan.nbytes == 4 * 1024
        assert plan.est_transfer_s < plan.est_recompute_s
        directive = plan.to_directive()
        assert directive["plan_id"] == plan.plan_id
        assert directive["block_keys"] == self.KEYS
        assert planner.get(plan.plan_id) is plan

    def test_holder_not_overloaded(self):
        planner = make_planner()
        plan, outcome = planner.plan(
            {"pod-1": prov(4.0, 4)},
            {"pod-1": 1.0, "pod-2": 0.0},
            self.KEYS,
        )
        assert plan is None and outcome == "holder-not-overloaded"

    def test_no_holder(self):
        planner = make_planner()
        plan, outcome = planner.plan(
            {"pod-1": prov(0.0, 0)}, {"pod-1": 9.0}, self.KEYS
        )
        assert plan is None and outcome == "no-holder"

    def test_too_few_blocks(self):
        planner = make_planner(min_blocks=3)
        plan, outcome = planner.plan(
            {"pod-1": prov(2.0, 2)},
            {"pod-1": 9.0, "pod-2": 0.0},
            self.KEYS[:2],
        )
        assert plan is None and outcome == "too-few-blocks"

    def test_no_target(self):
        planner = make_planner()
        # Every other pod is at least as loaded as the holder.
        plan, outcome = planner.plan(
            {"pod-1": prov(4.0, 4)},
            {"pod-1": 5.0, "pod-2": 5.0},
            self.KEYS,
        )
        assert plan is None and outcome == "no-target"

    def test_no_target_without_headroom(self):
        # Less loaded than the holder is not enough: a saturated pod
        # is not a transfer target (load >= load_threshold / 2).
        planner = make_planner(load_threshold=6.0)
        plan, outcome = planner.plan(
            {"pod-1": prov(4.0, 4)},
            {"pod-1": 9.0, "pod-2": 4.0},
            self.KEYS,
        )
        assert plan is None and outcome == "no-target"
        plan, outcome = planner.plan(
            {"pod-1": prov(4.0, 4)},
            {"pod-1": 9.0, "pod-2": 2.0},
            self.KEYS,
        )
        assert outcome == "planned" and plan.target_pod == "pod-2"

    def test_recompute_cheaper(self):
        advisor = fed_advisor(load_s=None, store_s=None)
        advisor.observe_load(1024, 100.0)  # absurdly slow readback
        planner = make_planner(advisor)
        plan, outcome = planner.plan(
            {"pod-1": prov(4.0, 4)},
            {"pod-1": 9.0, "pod-2": 0.0},
            self.KEYS,
        )
        assert plan is None and outcome == "recompute-cheaper"

    def test_no_block_bytes(self):
        planner = make_planner(fed_advisor(bytes_per_block=0))
        plan, outcome = planner.plan(
            {"pod-1": prov(4.0, 4)},
            {"pod-1": 9.0, "pod-2": 0.0},
            self.KEYS,
        )
        assert plan is None and outcome == "no-block-bytes"

    def test_zero_rtt_estimator_never_plans(self):
        # ISSUE edge case: no RTT measurements at all -> recompute is
        # the only priced option; the planner must not plan on a guess.
        advisor = fed_advisor(load_s=None, store_s=None)
        assert advisor.rtt.estimate(4096) is None
        planner = make_planner(advisor)
        plan, outcome = planner.plan(
            {"pod-1": prov(4.0, 4)},
            {"pod-1": 9.0, "pod-2": 0.0},
            self.KEYS,
        )
        assert plan is None and outcome == "no-rtt-observations"
        assert planner.stats()["outcomes"] == {"no-rtt-observations": 1}

    def test_no_prefill_rate_still_plans_flagged(self):
        advisor = ComputeOrLoadAdvisor(
            AdvisorConfig(bytes_per_block=1024, block_tokens=BLOCK_SIZE)
        )
        advisor.observe_load(4096, 0.001)
        advisor.observe_store(4096, 0.0005)
        assert advisor.prefill_tokens_per_s is None
        planner = make_planner(advisor)
        plan, outcome = planner.plan(
            {"pod-1": prov(4.0, 4)},
            {"pod-1": 9.0, "pod-2": 0.0},
            self.KEYS,
        )
        assert outcome == "planned"
        assert plan.reason == "no-prefill-rate"
        assert plan.est_recompute_s is None

    def test_determinism_under_fixed_snapshot(self):
        # ISSUE edge case: two fresh planners fed the identical
        # snapshot produce byte-identical directives (no wall clock,
        # no randomness, counter ids, lexicographic tiebreaks).
        per_pod = {
            "pod-b": prov(4.0, 4),
            "pod-a": prov(4.0, 4),  # score tie -> lexicographic holder
            "pod-c": prov(0.0, 0),
        }
        loads = {"pod-a": 9.0, "pod-b": 9.0, "pod-c": 0.0, "pod-d": 0.0}
        directives = []
        for _ in range(2):
            planner = make_planner(fed_advisor())
            plan, outcome = planner.plan(
                per_pod,
                dict(loads),
                self.KEYS,
                token_ids=list(range(16)),
                block_size=BLOCK_SIZE,
                now=0.0,
            )
            assert outcome == "planned"
            directives.append(plan.to_directive())
        assert directives[0] == directives[1]
        assert directives[0]["source_pod"] == "pod-a"
        # min-load tiebreak is lexicographic too.
        assert directives[0]["target_pod"] == "pod-c"

    def test_replan_damping_in_flight(self):
        # Scoring the same hot chain again while a plan is live must
        # not mint a duplicate transfer (pool-thrash guard).
        planner = make_planner()
        snapshot = (
            {"pod-1": prov(4.0, 4)},
            {"pod-1": 9.0, "pod-2": 0.0},
            self.KEYS,
        )
        plan, outcome = planner.plan(*snapshot, now=0.0)
        assert outcome == "planned"
        dup, outcome = planner.plan(*snapshot, now=0.0)
        assert dup is None and outcome == "in-flight"
        # After the plan lands, the same chain -> same target is still
        # cooled down; a different chain is unaffected.
        planner.mark(plan.plan_id, DONE)
        dup, outcome = planner.plan(*snapshot, now=1.0)
        assert dup is None and outcome == "recently-transferred"
        other, outcome = planner.plan(
            snapshot[0], snapshot[1], [77, 88, 99, 110], now=1.0
        )
        assert outcome == "planned" and other is not None

    def test_replan_cooldown_expires(self):
        planner = make_planner(replan_cooldown_s=5.0)
        snapshot = (
            {"pod-1": prov(4.0, 4)},
            {"pod-1": 9.0, "pod-2": 0.0},
            self.KEYS,
        )
        plan, _ = planner.plan(*snapshot, now=0.0)
        planner.mark(plan.plan_id, DONE)
        _, outcome = planner.plan(*snapshot, now=4.0)
        assert outcome == "recently-transferred"
        again, outcome = planner.plan(*snapshot, now=5.0)
        assert outcome == "planned" and again.plan_id != plan.plan_id

    def test_ttl_expiry(self):
        planner = make_planner(ttl_s=10.0)
        plan, _ = planner.plan(
            {"pod-1": prov(4.0, 4)},
            {"pod-1": 9.0, "pod-2": 0.0},
            self.KEYS,
            now=0.0,
        )
        assert planner.expire(now=5.0) == 0
        assert planner.expire(now=10.0) == 1
        assert plan.state == EXPIRED

    def test_invalidate_pod(self):
        planner = make_planner()
        plan, _ = planner.plan(
            {"pod-1": prov(4.0, 4)},
            {"pod-1": 9.0, "pod-2": 0.0},
            self.KEYS,
        )
        assert planner.invalidate_pod("pod-3") == 0
        assert planner.invalidate_pod("pod-2") == 1
        assert plan.state == INVALIDATED

    def test_registry_bounded(self):
        planner = make_planner(max_plans=3)
        for _ in range(5):
            planner.plan_warmup("pod-1", "pod-2", self.KEYS)
        stats = planner.stats()
        assert stats["plans"] == 3
        assert planner.get(1) is None and planner.get(5) is not None


# ----------------------------- executor ---------------------------------


class TestExecutor:
    def _seeded(self, n_blocks=8):
        indexer, pool = make_stack()
        tokens = list(range(1, n_blocks * BLOCK_SIZE + 1))
        engine_hashes = [0x7000 + i for i in range(n_blocks)]
        seed_chain(pool, "pod-1", engine_hashes, tokens)
        request_keys = indexer.token_processor.tokens_to_kv_block_keys(
            0, tokens, MODEL
        )
        return indexer, pool, tokens, engine_hashes, request_keys

    def test_copy_flips_target_score(self):
        indexer, pool, tokens, hashes, keys = self._seeded()
        try:
            prompt = prompt_of(tokens)
            before = indexer.get_pod_scores(
                prompt, MODEL, ["pod-1", "pod-2"]
            )
            assert before == {"pod-1": 8.0, "pod-2": 0.0}
            planner = make_planner()
            plan = planner.plan_warmup(
                "pod-1",
                "pod-2",
                keys,
                engine_hashes=hashes,
                token_ids=tokens,
                block_size=BLOCK_SIZE,
            )
            executor = TransferExecutor(
                indexer.kv_block_index, pool, MODEL
            )
            assert executor.execute(plan) is True
            assert plan.state == DONE
            pool.drain()
            after = indexer.get_pod_scores(
                prompt, MODEL, ["pod-1", "pod-2"]
            )
            # Copy: the target warms, the source keeps its residency.
            assert after == {"pod-1": 8.0, "pod-2": 8.0}
            # Re-executing a DONE plan is a stale no-op.
            assert executor.execute(plan) is False
        finally:
            pool.shutdown()
            indexer.shutdown()

    def test_source_dies_mid_plan_no_phantom_entries(self):
        # ISSUE edge case: the source evaporates between plan and
        # execute.  The plan is invalidated and NO events flow — the
        # target must not gain phantom residency.
        indexer, pool, tokens, hashes, keys = self._seeded()
        try:
            planner = make_planner()
            plan = planner.plan_warmup(
                "pod-1",
                "pod-2",
                keys,
                engine_hashes=hashes,
                token_ids=tokens,
                block_size=BLOCK_SIZE,
            )
            # Source dies: its whole chain is evicted.
            publish(
                pool,
                "pod-1",
                [BlockRemoved(block_hashes=hashes, medium="hbm")],
            )
            executor = TransferExecutor(
                indexer.kv_block_index, pool, MODEL
            )
            assert executor.execute(plan) is False
            assert plan.state == INVALIDATED
            assert executor.stats()["invalidated"] == 1
            pool.drain()
            found = indexer.kv_block_index.lookup(keys)
            assert found == {}, "phantom entries planted at the target"
            scores = indexer.get_pod_scores(
                prompt_of(tokens), MODEL, ["pod-2"]
            )
            assert scores == {"pod-2": 0.0}
        finally:
            pool.shutdown()
            indexer.shutdown()

    def test_transfer_vs_demotion_race_uses_current_tier(self):
        # ISSUE edge case: a demotion moves the chain hbm -> host
        # between plan and execute.  A move must remove the source's
        # CURRENT entries (host); removing the plan-time tier (hbm)
        # would leave the host residency behind forever.
        indexer, pool, tokens, hashes, keys = self._seeded()
        try:
            planner = make_planner()
            plan = planner.plan_warmup(
                "pod-1",
                "pod-2",
                keys,
                engine_hashes=hashes,
                token_ids=tokens,
                block_size=BLOCK_SIZE,
                tier="hbm",  # plan-time observation, about to go stale
            )
            # Demotion worker moves the chain down a rung
            # (store-before-remove, same as tiering/demotion.py).
            publish(
                pool,
                "pod-1",
                [
                    BlockStored(
                        block_hashes=hashes,
                        parent_block_hash=None,
                        token_ids=tokens,
                        block_size=BLOCK_SIZE,
                        medium="host",
                    ),
                    BlockRemoved(block_hashes=hashes, medium="hbm"),
                ],
            )
            executor = TransferExecutor(
                indexer.kv_block_index, pool, MODEL
            )
            assert executor.execute(plan, mode="move") is True
            pool.drain()
            found = indexer.kv_block_index.lookup(keys)
            residency = {
                (entry.pod_identifier, entry.device_tier)
                for pods in found.values()
                for entry in pods
            }
            # Source fully gone (removed at host, the tier it actually
            # held), target warmed at hbm.
            assert residency == {("pod-2", "hbm")}
        finally:
            pool.shutdown()
            indexer.shutdown()

    def test_partial_surviving_prefix(self):
        indexer, pool, tokens, hashes, keys = self._seeded()
        try:
            planner = make_planner()
            plan = planner.plan_warmup(
                "pod-1",
                "pod-2",
                keys,
                engine_hashes=hashes,
                token_ids=tokens,
                block_size=BLOCK_SIZE,
            )
            # The source evicts the tail half of the chain.
            publish(
                pool,
                "pod-1",
                [BlockRemoved(block_hashes=hashes[4:], medium="hbm")],
            )
            executor = TransferExecutor(
                indexer.kv_block_index, pool, MODEL
            )
            assert executor.execute(plan) is True
            pool.drain()
            scores = indexer.get_pod_scores(
                prompt_of(tokens), MODEL, ["pod-2"]
            )
            # Only the surviving 4-block prefix moved.
            assert scores == {"pod-2": 4.0}
        finally:
            pool.shutdown()
            indexer.shutdown()


# ------------------------------ warm-up ---------------------------------


class TestWarmup:
    def test_catalog_longer_chain_wins(self):
        catalog = HotFamilyCatalog(max_families=2)
        catalog.note(1, "pod-1", [11, 22, 33], now=1.0)
        # A shorter observation refreshes recency, keeps the chain.
        catalog.note(1, "pod-2", [11], now=2.0)
        record = catalog.get(1)
        assert record.block_keys == [11, 22, 33]
        assert record.holder_pod == "pod-1"
        # A longer observation replaces it (and may change holder).
        catalog.note(1, "pod-2", [11, 22, 33, 44], now=3.0)
        assert catalog.get(1).holder_pod == "pod-2"
        # Bounded: a third family evicts the oldest.
        catalog.note(2, "pod-1", [55], now=4.0)
        catalog.note(3, "pod-1", [66], now=5.0)
        assert catalog.stats()["families"] == 2
        assert catalog.get(1) is None

    def test_ledger_ranked_families(self):
        ledger = CacheStatsLedger(LedgerConfig(sample_rate=1.0))
        # Family 0xB is hotter (shorter reuse interval) than 0xA.
        ledger.record(0xA, MODEL, 4, 4, now=0.0)
        ledger.record(0xA, MODEL, 4, 4, now=10.0)
        ledger.record(0xB, MODEL, 4, 4, now=8.0)
        ledger.record(0xB, MODEL, 4, 4, now=10.0)
        catalog = HotFamilyCatalog()
        catalog.note(0xA, "pod-1", [1, 2])
        catalog.note(0xB, "pod-1", [3, 4])
        worker = WarmupWorker(
            catalog, make_planner(), executor=None, ledger=ledger,
            warmup_families=1,
        )
        assert worker._ranked_families() == [0xB]

    def test_cold_pod_warms_through_real_events(self):
        ledger = CacheStatsLedger(LedgerConfig(sample_rate=1.0))
        indexer, pool = make_stack(ledger=ledger)
        engine = TransferEngine(
            advisor=fed_advisor(),
            ledger=ledger,
            config=TransferConfig(load_threshold=2.0, warmup_moves=2),
        )
        indexer.set_transfer_engine(engine)
        engine.attach_executor(
            indexer.kv_block_index, pool, MODEL, start_warmup=False
        )
        try:
            tokens = list(range(1, 17))  # 4 blocks
            hashes = [0x8800 + i for i in range(4)]
            seed_chain(pool, "pod-1", hashes, tokens)
            prompt = prompt_of(tokens)
            # Scored traffic feeds the hot-family catalog.
            for _ in range(2):
                indexer.get_pod_scores_planned(
                    prompt, MODEL, ["pod-1", "pod-2"]
                )
            assert engine.catalog.stats()["families"] == 1
            # A new pod joins cold and registers.
            queued = engine.register_cold_pod("pod-3")
            assert queued == 1
            assert engine.warmup.status()["cold_pods"] == {"pod-3": 1}
            # The budgeted worker drains the queue; events are real.
            assert engine.run_warmup_cycle() == 1
            pool.drain()
            scores = indexer.get_pod_scores(
                prompt, MODEL, ["pod-1", "pod-3"]
            )
            assert scores["pod-3"] == scores["pod-1"] == 4.0
            status = engine.warmup.status()
            assert status["cold_pods"] == {}
            assert status["warmed_moves"] == {"pod-3": 1}
        finally:
            engine.close()
            pool.shutdown()
            indexer.shutdown()

    def test_register_cold_pod_skips_self_holder(self):
        catalog = HotFamilyCatalog()
        catalog.note(1, "pod-1", [11, 22])
        worker = WarmupWorker(catalog, make_planner(), executor=None)
        assert worker.register_cold_pod("pod-1") == 0


# --------------------------- load blending ------------------------------


class TestLoadBlend:
    def _seeded_indexer(self, **config_kw):
        indexer, pool = make_stack(**config_kw)
        tokens = list(range(1, 17))
        seed_chain(
            pool, "pod-1", [0x9900 + i for i in range(4)], tokens
        )
        return indexer, pool, prompt_of(tokens)

    def test_blend_divides_by_queue_depth(self):
        indexer, pool, prompt = self._seeded_indexer(load_blend=0.5)
        try:
            scores = indexer.get_pod_scores(
                prompt,
                MODEL,
                ["pod-1", "pod-2"],
                pod_loads={"pod-1": 2.0},
            )
            # 4.0 / (1 + 0.5 * 2) = 2.0; unloaded pod untouched.
            assert scores == {"pod-1": 2.0, "pod-2": 0.0}
        finally:
            pool.shutdown()
            indexer.shutdown()

    def test_parity_when_disabled(self):
        indexer, pool, prompt = self._seeded_indexer(load_blend=0.0)
        try:
            plain = indexer.get_pod_scores(prompt, MODEL, ["pod-1"])
            loaded = indexer.get_pod_scores(
                prompt, MODEL, ["pod-1"], pod_loads={"pod-1": 50.0}
            )
            assert plain == loaded == {"pod-1": 4.0}
        finally:
            pool.shutdown()
            indexer.shutdown()

    def test_explain_shows_the_blend(self):
        indexer, pool, prompt = self._seeded_indexer(load_blend=0.5)
        try:
            scores, detail = indexer.get_pod_scores_explained(
                prompt,
                MODEL,
                ["pod-1"],
                pod_loads={"pod-1": 2.0},
            )
            assert scores == {"pod-1": 2.0}
            blend = detail["load_blend"]
            assert blend["coefficient"] == 0.5
            assert blend["pods"]["pod-1"] == {
                "raw": 4.0,
                "queue_depth": 2.0,
                "blended": 2.0,
            }
        finally:
            pool.shutdown()
            indexer.shutdown()

    def test_env_default(self, monkeypatch):
        from llm_d_kv_cache_manager_tpu.kvcache.indexer import (
            _env_load_blend_default,
        )

        monkeypatch.delenv("LOAD_BLEND", raising=False)
        assert _env_load_blend_default() == 0.0
        monkeypatch.setenv("LOAD_BLEND", "0.25")
        assert _env_load_blend_default() == 0.25
        monkeypatch.setenv("LOAD_BLEND", "bogus")
        assert _env_load_blend_default() == 0.0


# ------------------------ unknown-pod zero-fill -------------------------


class TestUnknownPodZeroFill:
    def _check(self, **config_kw):
        indexer, pool = make_stack(**config_kw)
        try:
            tokens = list(range(1, 17))
            seed_chain(
                pool, "pod-1", [0xAA00 + i for i in range(4)], tokens
            )
            prompt = prompt_of(tokens)
            for _ in range(2):  # second pass exercises the memo lane
                scores = indexer.get_pod_scores(
                    prompt, MODEL, ["pod-1", "ghost-pod"]
                )
                assert scores == {"pod-1": 4.0, "ghost-pod": 0.0}
            scores, detail = indexer.get_pod_scores_explained(
                prompt, MODEL, ["pod-1", "ghost-pod"]
            )
            assert scores["ghost-pod"] == 0.0
            # The explain provenance agrees with the score dict: the
            # planner and the ledger see the same candidate set.
            assert detail["pods"]["ghost-pod"] == {
                "score": 0.0,
                "blocks_matched": 0,
                "break_index": 0,
                "tiers": {},
            }
        finally:
            pool.shutdown()
            indexer.shutdown()

    def test_fast_lane(self):
        self._check(read_path_fast_lane=True)

    def test_straight_lane(self):
        self._check(read_path_fast_lane=False)


# ---------------------- engine + planned variant ------------------------


class TestTransferEngine:
    def test_config_from_env(self, monkeypatch):
        monkeypatch.setenv("TRANSFER_LOAD_THRESHOLD", "7.5")
        monkeypatch.setenv("TRANSFER_MIN_BLOCKS", "3")
        monkeypatch.setenv("TRANSFER_WARMUP_MOVES", "9")
        monkeypatch.setenv("TRANSFER_TTL_S", "bogus")  # warn + default
        config = TransferConfig.from_env()
        assert config.load_threshold == 7.5
        assert config.min_blocks == 3
        assert config.warmup_moves == 9
        assert config.ttl_s == 30.0

    def test_plan_for_chain_directive_shape(self):
        engine = TransferEngine(
            advisor=fed_advisor(),
            config=TransferConfig(load_threshold=2.0),
        )
        directive = engine.plan_for_chain(
            {"pod-1": prov(4.0, 4), "pod-2": prov(0.0, 0)},
            {"pod-1": 5.0, "pod-2": 0.0},
            [11, 22, 33, 44],
            token_ids=list(range(16)),
            block_size=BLOCK_SIZE,
        )
        assert directive["planned"] is True
        assert directive["outcome"] == "planned"
        assert directive["source_pod"] == "pod-1"
        assert directive["target_pod"] == "pod-2"
        # The same call with a calm holder reports why it declined.
        declined = engine.plan_for_chain(
            {"pod-1": prov(4.0, 4)},
            {"pod-1": 0.0},
            [11, 22, 33, 44],
        )
        assert declined == {
            "planned": False,
            "outcome": "holder-not-overloaded",
        }
        # Either way the catalog learned the holder.
        assert engine.catalog.stats()["families"] == 1

    def test_planned_scoring_variant(self):
        indexer, pool = make_stack()
        engine = TransferEngine(
            advisor=fed_advisor(),
            config=TransferConfig(load_threshold=2.0),
        )
        indexer.set_transfer_engine(engine)
        try:
            tokens = list(range(1, 17))
            seed_chain(
                pool, "pod-1", [0xBB00 + i for i in range(4)], tokens
            )
            prompt = prompt_of(tokens)
            scores, directive = indexer.get_pod_scores_planned(
                prompt,
                MODEL,
                ["pod-1", "pod-2"],
                pod_loads={"pod-1": 9.0, "pod-2": 0.0},
            )
            assert scores["pod-1"] == 4.0
            assert directive["planned"] is True
            assert directive["target_pod"] == "pod-2"
        finally:
            engine.close()
            pool.shutdown()
            indexer.shutdown()


# ------------------------- HTTP debug surface ---------------------------


def _post(base, path, payload):
    req = urllib.request.Request(
        base + path,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=10) as response:
        return json.load(response)


class TestTransferHttpSurface:
    def test_debug_endpoint_healthz_and_planned_scoring(self):
        indexer, pool = make_stack()
        engine = TransferEngine(
            advisor=fed_advisor(),
            config=TransferConfig(load_threshold=2.0),
        )
        indexer.set_transfer_engine(engine)
        server = serve(
            indexer, host="127.0.0.1", port=0, transfer=engine
        )
        base = f"http://127.0.0.1:{server.server_address[1]}"
        try:
            tokens = list(range(1, 17))
            seed_chain(
                pool, "pod-1", [0xCC00 + i for i in range(4)], tokens
            )
            reply = _post(
                base,
                "/score_completions",
                {
                    "prompt": prompt_of(tokens),
                    "model": MODEL,
                    "pods": ["pod-1", "pod-2"],
                    "pod_loads": {"pod-1": 9.0, "pod-2": 0.0},
                    "plan": True,
                },
            )
            assert reply["scores"]["pod-1"] == 4.0
            assert reply["transfer"]["planned"] is True
            assert reply["transfer"]["target_pod"] == "pod-2"
            with urllib.request.urlopen(
                base + "/debug/transfer", timeout=10
            ) as response:
                payload = json.load(response)
            assert payload["planner"]["outcomes"]["planned"] == 1
            assert payload["catalog"]["families"] == 1
            assert payload["config"]["load_threshold"] == 2.0
            with urllib.request.urlopen(
                base + "/healthz", timeout=10
            ) as response:
                health = json.load(response)
            assert health["transfer"]["plans"] == 1
            with urllib.request.urlopen(
                base + "/debug", timeout=10
            ) as response:
                debug_index = json.load(response)
            surfaces = {
                row["path"]: row["enabled"]
                for row in debug_index["surfaces"]
            }
            assert surfaces["/debug/transfer"] is True
        finally:
            server.shutdown()
            engine.close()
            pool.shutdown()
            indexer.shutdown()

    def test_debug_endpoint_404_when_disabled(self):
        indexer, pool = make_stack()
        server = serve(indexer, host="127.0.0.1", port=0)
        base = f"http://127.0.0.1:{server.server_address[1]}"
        try:
            try:
                urllib.request.urlopen(
                    base + "/debug/transfer", timeout=10
                )
                raise AssertionError("expected 404")
            except urllib.error.HTTPError as exc:
                assert exc.code == 404
        finally:
            server.shutdown()
            pool.shutdown()
            indexer.shutdown()

    def test_malformed_pod_loads_rejected(self):
        indexer, pool = make_stack()
        server = serve(indexer, host="127.0.0.1", port=0)
        base = f"http://127.0.0.1:{server.server_address[1]}"
        try:
            try:
                _post(
                    base,
                    "/score_completions",
                    {
                        "prompt": "t1 t2",
                        "model": MODEL,
                        "pod_loads": {"pod-1": "busy"},
                    },
                )
                raise AssertionError("expected 400")
            except urllib.error.HTTPError as exc:
                assert exc.code == 400
        finally:
            server.shutdown()
            pool.shutdown()
            indexer.shutdown()
