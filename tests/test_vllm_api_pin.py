"""Pin the vLLM v1 kv_offload API surface the adapter implements.

Round-2 flagged that ``offload/vllm_spec.py`` had never met real vLLM:
its tests exercise duck-typed stand-ins, so silent drift between our
adapter and the real ``vllm.v1.kv_offload`` ABCs would pass every test
and fail only inside a serving pod.  This module closes that hole from
both ends:

* ``PINNED_API`` records the abstract surface as used by the reference
  adapter (kv_connectors/llmd_fs_backend/llmd_fs_backend/{spec,manager,
  worker}.py — the same vLLM contract we target).
* The adapter classes are checked against the pin ALWAYS (no vllm
  needed): every pinned method must exist with the pinned positional
  parameters.
* When real vllm IS importable (inside a serving image's CI), the pin is
  checked against the live ABCs, so an upstream signature change fails
  here first with a message naming the drift.
"""

from __future__ import annotations

import inspect

from llm_d_kv_cache_manager_tpu.offload import vllm_spec

# class name in vllm.v1.kv_offload -> {method: positional params}.
PINNED_API = {
    "OffloadingManager": {
        "lookup": ["self", "block_hashes"],
        "prepare_load": ["self", "block_hashes"],
        "touch": ["self", "block_hashes"],
        "complete_load": ["self", "block_hashes"],
        "prepare_store": ["self", "block_hashes"],
        "complete_store": ["self", "block_hashes", "success"],
    },
    "OffloadingSpec": {
        "__init__": ["self", "vllm_config", "kv_cache_config"],
        "get_manager": ["self"],
        "get_handlers": ["self", "kv_caches", "attn_backends"],
    },
    "OffloadingHandler": {
        "transfer_async": ["self", "job_id", "spec"],
        "get_finished": ["self"],
    },
}

# Fields PrepareStoreOutput must accept (reference manager.py:92-97).
PINNED_PREPARE_STORE_FIELDS = [
    "block_hashes_to_store",
    "store_spec",
    "block_hashes_evicted",
]

ADAPTERS = {
    "OffloadingManager": vllm_spec.TPUSharedStorageOffloadingManager,
    "OffloadingSpec": vllm_spec.TPUSharedStorageOffloadingSpec,
    "OffloadingHandler": vllm_spec.TPUToStorageHandler,
}


def _positional_params(func) -> list:
    sig = inspect.signature(func)
    return [
        name
        for name, p in sig.parameters.items()
        if p.kind
        in (
            inspect.Parameter.POSITIONAL_ONLY,
            inspect.Parameter.POSITIONAL_OR_KEYWORD,
        )
    ]


class TestAdapterMatchesPin:
    """Our classes implement every pinned method, pinned-compatibly."""

    def test_manager_methods(self):
        cls = ADAPTERS["OffloadingManager"]
        for method, params in PINNED_API["OffloadingManager"].items():
            fn = getattr(cls, method, None)
            assert fn is not None, f"manager adapter missing {method}"
            assert _positional_params(fn)[: len(params)] == params, (
                f"manager.{method} signature drifted from the vLLM pin"
            )

    def test_handler_methods(self):
        for cls in (
            vllm_spec.TPUToStorageHandler,
            vllm_spec.StorageToTPUHandler,
        ):
            for method, params in PINNED_API["OffloadingHandler"].items():
                fn = getattr(cls, method, None)
                assert fn is not None, f"{cls.__name__} missing {method}"
                assert _positional_params(fn)[: len(params)] == params

    def test_spec_methods(self):
        cls = ADAPTERS["OffloadingSpec"]
        for method, params in PINNED_API["OffloadingSpec"].items():
            fn = getattr(cls, method, None)
            assert fn is not None, f"spec adapter missing {method}"
            assert _positional_params(fn)[: len(params)] == params

    def test_prepare_store_output_fields(self):
        out = vllm_spec.TPUSharedStorageOffloadingManager.prepare_store(
            # unbound call with a stub self: prepare_store touches no state
            object.__new__(vllm_spec.TPUSharedStorageOffloadingManager),
            [1, 2, 3],
        )
        for field in PINNED_PREPARE_STORE_FIELDS:
            assert hasattr(out, field), f"PrepareStoreOutput lacks {field}"
        assert out.block_hashes_to_store == [1, 2, 3]
        assert out.block_hashes_evicted == []

    def test_mediums(self):
        assert vllm_spec.GPULoadStoreSpec.medium() == "GPU"
        assert (
            vllm_spec.TPUSharedStorageLoadStoreSpec.medium()
            == "SHARED_STORAGE"
        )


class TestPinMatchesRealVllm:
    """With real vllm installed, the pin must match the live ABCs."""

    def test_live_abstract_surface(self):
        import pytest

        vllm_abstract = pytest.importorskip("vllm.v1.kv_offload.abstract")
        from vllm.v1.kv_offload.spec import OffloadingSpec
        from vllm.v1.kv_offload.worker.worker import OffloadingHandler

        live = {
            "OffloadingManager": vllm_abstract.OffloadingManager,
            "OffloadingSpec": OffloadingSpec,
            "OffloadingHandler": OffloadingHandler,
        }
        for cls_name, methods in PINNED_API.items():
            cls = live[cls_name]
            for method, params in methods.items():
                fn = getattr(cls, method, None)
                assert fn is not None, (
                    f"vllm {cls_name} no longer has {method}; update "
                    "the adapter AND this pin"
                )
                live_params = _positional_params(fn)
                assert live_params[: len(params)] == params, (
                    f"vllm {cls_name}.{method} drifted: now {live_params}"
                )
            # New abstract requirements we don't pin => adapter breaks.
            abstract = set(getattr(cls, "__abstractmethods__", ()))
            unknown = abstract - set(methods)
            assert not unknown, (
                f"vllm {cls_name} grew abstract methods {sorted(unknown)} "
                "the adapter does not implement"
            )

    def test_adapter_is_real_subclass(self):
        import pytest

        pytest.importorskip("vllm.v1.kv_offload.abstract")
        assert vllm_spec.HAVE_VLLM
        from vllm.v1.kv_offload.abstract import OffloadingManager

        assert issubclass(
            vllm_spec.TPUSharedStorageOffloadingManager, OffloadingManager
        )
