"""vLLM OffloadingSpec adapter: layout inference, roundtrip, budget.

vLLM itself is not installed in this image; these tests drive the
adapter through duck-typed stand-ins for vLLM's config objects and
attention backends, covering the reference's three KV layouts
(kv_connectors/llmd_fs_backend/llmd_fs_backend/worker.py:270-346) and
the staging-memory bound (worker.py:191-216).
"""

import threading
import time
from dataclasses import dataclass, field

import numpy as np
import pytest

from llm_d_kv_cache_manager_tpu.offload import vllm_spec
from llm_d_kv_cache_manager_tpu.offload.staging import StagingBudget
from llm_d_kv_cache_manager_tpu.offload.vllm_spec import (
    GPULoadStoreSpec,
    TPUSharedStorageLoadStoreSpec,
    TPUSharedStorageOffloadingSpec,
    infer_kv_tensor_views,
)

# --- vLLM config stand-ins -------------------------------------------------


@dataclass
class CacheConfig:
    block_size: int = 16
    cache_dtype: str = "auto"


@dataclass
class ModelConfig:
    model: str = "test/model"
    dtype: str = "float32"


@dataclass
class ParallelConfig:
    tensor_parallel_size: int = 1
    pipeline_parallel_size: int = 1
    prefill_context_parallel_size: int = 1
    rank: int = 0

    @property
    def world_size(self) -> int:
        return (
            self.tensor_parallel_size
            * self.pipeline_parallel_size
            * self.prefill_context_parallel_size
        )


@dataclass
class KVTransferConfig:
    kv_connector_extra_config: dict = field(default_factory=dict)


@dataclass
class VllmConfig:
    cache_config: CacheConfig = field(default_factory=CacheConfig)
    model_config: ModelConfig = field(default_factory=ModelConfig)
    parallel_config: ParallelConfig = field(default_factory=ParallelConfig)
    kv_transfer_config: KVTransferConfig = field(
        default_factory=KVTransferConfig
    )


# --- attention-backend stand-ins ------------------------------------------


class StandardBackend:
    """vLLM FlashAttention-style: (num_blocks, block_size, heads, head)."""

    @staticmethod
    def get_kv_cache_shape(num_blocks, block_size, num_kv_heads, head_size):
        return (num_blocks, block_size, num_kv_heads, head_size)


class SplitKVBackend:
    """(2, num_blocks, heads, block_size, head_size) — K/V split."""

    @staticmethod
    def get_kv_cache_shape(num_blocks, block_size, num_kv_heads, head_size):
        return (2, num_blocks, num_kv_heads, block_size, head_size)


class CrossLayerBackend(StandardBackend):
    """Per-layer shape; the live tensor carries an extra layer dimension
    and its stride order puts num_blocks ahead of the layer stack
    (physical layout ``(num_blocks, L, block_size, heads, head)``)."""

    @staticmethod
    def get_kv_cache_stride_order(include_num_layers_dimension=False):
        if include_num_layers_dimension:
            return (1, 0, 2, 3, 4)
        return (0, 1, 2, 3)


class StrideOrderBackend:
    """Backend whose canonical order permutes block_size elsewhere."""

    @staticmethod
    def get_kv_cache_shape(num_blocks, block_size, num_kv_heads, head_size):
        return (num_blocks, num_kv_heads, block_size, head_size)

    @staticmethod
    def get_kv_cache_stride_order(include_num_layers_dimension=False):
        assert not include_num_layers_dimension
        return (0, 2, 1, 3)  # heads and block_size swapped in memory


def spec_for(tmp_path, extra=None, block_size=16):
    config = VllmConfig(
        cache_config=CacheConfig(block_size=block_size),
        kv_transfer_config=KVTransferConfig(
            {
                "shared_storage_path": str(tmp_path / "kv"),
                **(extra or {}),
            }
        ),
    )
    kv_cache_config = object()
    return TPUSharedStorageOffloadingSpec(config, kv_cache_config)


# --- layout inference ------------------------------------------------------


class TestLayoutInference:
    def test_standard_layout(self):
        caches = {
            "l0": np.zeros((8, 16, 2, 4), np.float32),
            "l1": np.zeros((8, 16, 2, 4), np.float32),
        }
        backends = {"l0": StandardBackend, "l1": StandardBackend}
        views, kernel_bs = infer_kv_tensor_views(caches, backends)
        assert len(views) == 2 and kernel_bs == 16

    def test_split_kv_layout_doubles_views(self):
        caches = {"l0": np.zeros((2, 8, 2, 16, 4), np.float32)}
        views, kernel_bs = infer_kv_tensor_views(
            caches, {"l0": SplitKVBackend}
        )
        assert len(views) == 2 and kernel_bs == 16
        assert views[0].name == "l0.k" and views[1].name == "l0.v"
        # Views alias the parent K/V halves.
        views[0].tensor[0, 0, 0, 0] = 7.0
        assert caches["l0"][0, 0, 0, 0, 0] == 7.0

    def test_cross_layer_layout(self):
        # Physical (num_blocks=8, L=4, bs=16, H=2, D=4): blocks lead, so
        # one view covers all layers of a block (reference "Case 1").
        caches = {"all": np.zeros((8, 4, 16, 2, 4), np.float32)}
        views, kernel_bs = infer_kv_tensor_views(
            caches, {"all": CrossLayerBackend}
        )
        assert len(views) == 1 and kernel_bs == 16

    def test_stride_order_locates_block_size(self):
        # Canonical (nb, heads, bs, hs); stride order (0,2,1,3) says the
        # physical layout is (nb, bs, heads, hs).
        caches = {"l0": np.zeros((8, 16, 2, 4), np.float32)}
        views, kernel_bs = infer_kv_tensor_views(
            caches, {"l0": StrideOrderBackend}
        )
        assert kernel_bs == 16

    def test_mismatched_kernel_block_size_rejected(self):
        caches = {
            "l0": np.zeros((8, 16, 2, 4), np.float32),
            "l1": np.zeros((8, 8, 2, 4), np.float32),
        }
        with pytest.raises(ValueError, match="kernel block size"):
            infer_kv_tensor_views(
                caches, {"l0": StandardBackend, "l1": StandardBackend}
            )

    def test_unrecognized_rank_rejected(self):
        caches = {"l0": np.zeros((8, 16, 2, 4, 1, 1), np.float32)}
        with pytest.raises(ValueError, match="rank"):
            infer_kv_tensor_views(caches, {"l0": StandardBackend})


# --- spec construction -----------------------------------------------------


class TestSpecConstruction:
    def test_importable_without_vllm(self):
        assert vllm_spec.HAVE_VLLM is False  # this image has no vLLM

    def test_reads_extra_config(self, tmp_path):
        spec = spec_for(
            tmp_path,
            extra={"block_size": 64, "threads_per_chip": 2,
                   "max_staging_memory_gb": 1},
        )
        assert spec.blocks_per_file == 4
        assert spec.threads_per_chip == 2
        assert spec.max_staging_memory_gb == 1
        assert "test/model" in spec.file_mapper.get_file_name(0xABC)

    def test_rejects_misaligned_block_size(self, tmp_path):
        with pytest.raises(ValueError, match="multiple"):
            spec_for(tmp_path, extra={"block_size": 24})

    def test_rejects_world_size_mismatch(self, tmp_path):
        config = VllmConfig(
            parallel_config=ParallelConfig(tensor_parallel_size=2)
        )
        config.parallel_config.__class__ = type(
            "P", (), {"world_size": 3, **{
                k: getattr(config.parallel_config, k)
                for k in ("tensor_parallel_size", "pipeline_parallel_size",
                          "prefill_context_parallel_size", "rank")
            }}
        )
        with pytest.raises(ValueError, match="world_size"):
            TPUSharedStorageOffloadingSpec(config, object())

    def test_manager_rank0_only(self, tmp_path):
        spec = spec_for(tmp_path)
        spec.vllm_config.parallel_config.rank = 1
        with pytest.raises(RuntimeError, match="rank 0"):
            spec.get_manager()


# --- end-to-end roundtrip --------------------------------------------------


def run_roundtrip(tmp_path, caches, backends, n_blocks, extra=None):
    spec = spec_for(tmp_path, extra=extra)
    handlers = list(spec.get_handlers(caches, backends))
    (_, _, store), (_, _, load) = handlers
    assert handlers[0][0] is GPULoadStoreSpec
    assert handlers[0][1] is TPUSharedStorageLoadStoreSpec

    block_ids = list(range(n_blocks))
    bpf = spec.blocks_per_file
    n_files = -(-n_blocks // bpf)
    hashes = [0x1000 + i for i in range(n_files)]

    originals = {k: np.array(v, copy=True) for k, v in caches.items()}
    assert store.transfer_async(
        1, (GPULoadStoreSpec(block_ids), TPUSharedStorageLoadStoreSpec(hashes))
    )
    store.wait({1})

    manager = spec.get_manager()
    assert manager.lookup(hashes) == len(hashes)

    for cache in caches.values():
        cache[...] = 0
    assert load.transfer_async(
        2, (TPUSharedStorageLoadStoreSpec(hashes), GPULoadStoreSpec(block_ids))
    )
    load.wait({2})
    for name, cache in caches.items():
        np.testing.assert_array_equal(cache, originals[name], err_msg=name)
    return spec


class TestRoundtrip:
    def test_standard_two_layers(self, tmp_path):
        rng = np.random.default_rng(0)
        caches = {
            f"l{i}": rng.standard_normal((12, 16, 2, 4)).astype(np.float32)
            for i in range(2)
        }
        backends = {f"l{i}": StandardBackend for i in range(2)}
        run_roundtrip(tmp_path, caches, backends, n_blocks=12,
                      extra={"block_size": 64})

    def test_split_kv_partial_first_group(self, tmp_path):
        rng = np.random.default_rng(1)
        caches = {
            "l0": rng.standard_normal((2, 10, 2, 16, 4)).astype(np.float32)
        }
        # 10 blocks over bpf=4 -> first file partial (2 blocks), 2 full.
        run_roundtrip(tmp_path, caches, {"l0": SplitKVBackend}, n_blocks=10,
                      extra={"block_size": 64})

    def test_kernel_blocks_smaller_than_device_blocks(self, tmp_path):
        rng = np.random.default_rng(2)
        # kernel block 8, device block 16 -> 2 kernel blocks per block.
        caches = {
            "l0": rng.standard_normal((24, 8, 2, 4)).astype(np.float32)
        }
        run_roundtrip(tmp_path, caches, {"l0": StandardBackend}, n_blocks=12,
                      extra={"block_size": 32})

    def test_cross_layer_roundtrip(self, tmp_path):
        rng = np.random.default_rng(5)
        caches = {
            "all": rng.standard_normal((12, 4, 16, 2, 4)).astype(np.float32)
        }
        run_roundtrip(tmp_path, caches, {"all": CrossLayerBackend},
                      n_blocks=12, extra={"block_size": 64})

    def test_torch_bfloat16_bit_exact(self, tmp_path):
        torch = pytest.importorskip("torch")
        caches_t = {
            "l0": torch.randn(8, 16, 2, 4, dtype=torch.float32).to(
                torch.bfloat16
            )
        }
        spec = spec_for(tmp_path, extra={"block_size": 64})
        (_, _, store), (_, _, load) = spec.get_handlers(
            caches_t, {"l0": StandardBackend}
        )
        original = caches_t["l0"].clone()
        ids = list(range(8))
        store.transfer_async(
            1, (GPULoadStoreSpec(ids), TPUSharedStorageLoadStoreSpec([1, 2]))
        )
        store.wait({1})
        caches_t["l0"].zero_()
        load.transfer_async(
            2, (TPUSharedStorageLoadStoreSpec([1, 2]), GPULoadStoreSpec(ids))
        )
        load.wait({2})
        assert torch.equal(caches_t["l0"], original)

    def test_get_finished_reports_and_scatters(self, tmp_path):
        rng = np.random.default_rng(3)
        caches = {
            "l0": rng.standard_normal((8, 16, 2, 4)).astype(np.float32)
        }
        spec = spec_for(tmp_path, extra={"block_size": 64})
        (_, _, store), (_, _, load) = spec.get_handlers(
            caches, {"l0": StandardBackend}
        )
        original = caches["l0"].copy()
        ids = list(range(8))
        store.transfer_async(
            7, (GPULoadStoreSpec(ids), TPUSharedStorageLoadStoreSpec([5, 6]))
        )
        done = []
        while not done:
            done = store.get_finished()
        assert done == [(7, True)]
        caches["l0"][...] = 0
        load.transfer_async(
            8, (TPUSharedStorageLoadStoreSpec([5, 6]), GPULoadStoreSpec(ids))
        )
        done = []
        while not done:
            done = load.get_finished()
        assert done == [(8, True)]
        np.testing.assert_array_equal(caches["l0"], original)

    def test_missing_file_load_fails(self, tmp_path):
        caches = {"l0": np.zeros((8, 16, 2, 4), np.float32)}
        spec = spec_for(tmp_path, extra={"block_size": 64})
        (_, _, _store), (_, _, load) = spec.get_handlers(
            caches, {"l0": StandardBackend}
        )
        load.transfer_async(
            9,
            (
                TPUSharedStorageLoadStoreSpec([0xDEAD]),
                GPULoadStoreSpec(list(range(4))),
            ),
        )
        done = []
        while not done:
            done = load.get_finished()
        assert done == [(9, False)]


# --- completion routing ----------------------------------------------------


class TestCompletionRouting:
    """vLLM polls get_finished on EVERY handler against one shared
    engine; completions must route to the owning handler (advisor r2
    high finding: an unfiltered drain let the store handler consume a
    load job, skipping the scatter and leaking budget bytes)."""

    def _handlers(self, tmp_path):
        rng = np.random.default_rng(11)
        caches = {
            "l0": rng.standard_normal((8, 16, 2, 4)).astype(np.float32)
        }
        spec = spec_for(tmp_path, extra={"block_size": 64})
        (_, _, store), (_, _, load) = spec.get_handlers(
            caches, {"l0": StandardBackend}
        )
        return caches, store, load

    def test_handlers_share_one_router(self, tmp_path):
        _, store, load = self._handlers(tmp_path)
        assert store.router is load.router
        assert store.engine is load.engine

    def test_store_poll_does_not_consume_load_completion(self, tmp_path):
        caches, store, load = self._handlers(tmp_path)
        original = caches["l0"].copy()
        ids = list(range(8))
        assert store.transfer_async(
            1, (GPULoadStoreSpec(ids), TPUSharedStorageLoadStoreSpec([1, 2]))
        )
        store.wait({1})
        caches["l0"][...] = 0
        assert load.transfer_async(
            2, (TPUSharedStorageLoadStoreSpec([1, 2]), GPULoadStoreSpec(ids))
        )
        # Poll the WRONG handler until the engine has surely finished:
        # it must never report the load job (and must not scatter).
        deadline = time.monotonic() + 10
        while load.router._unclaimed.get(2) is None:
            assert store.get_finished() == []
            if time.monotonic() > deadline:
                pytest.fail("load job never reached the router")
            time.sleep(0.001)
        # The owning handler harvests it and the scatter lands.
        done = load.get_finished()
        assert done == [(2, True)]
        np.testing.assert_array_equal(caches["l0"], original)
        assert load.budget.in_flight_bytes == 0

    def test_wait_recovers_cross_drained_completion(self, tmp_path):
        caches, store, load = self._handlers(tmp_path)
        ids = list(range(8))
        assert store.transfer_async(
            3, (GPULoadStoreSpec(ids), TPUSharedStorageLoadStoreSpec([7]))
        )
        # The load handler's poll harvests the store job into the shared
        # router buffer; store.wait must still find it.
        deadline = time.monotonic() + 10
        while 3 not in store.router._unclaimed:
            assert load.get_finished() == []
            if time.monotonic() > deadline:
                pytest.fail("store job never reached the router")
            time.sleep(0.001)
        store.wait({3})
        assert store.budget.in_flight_bytes == 0


# --- staging budget --------------------------------------------------------


class TestBudgetBackpressure:
    """transfer_async must never block (advisor r2 medium): releases
    only happen on the same thread's later get_finished/wait calls, so
    a blocking acquire wedges the serving loop.  And the load path must
    acquire before allocating, or blocked submitters already hold their
    job's memory."""

    def _loaded_handlers(self, tmp_path, budget_bytes):
        rng = np.random.default_rng(12)
        caches = {
            "l0": rng.standard_normal((8, 16, 2, 4)).astype(np.float32)
        }
        spec = spec_for(
            tmp_path,
            extra={
                "block_size": 64,
                "max_staging_memory_gb": budget_bytes / (1 << 30),
            },
        )
        (_, _, store), (_, _, load) = spec.get_handlers(
            caches, {"l0": StandardBackend}
        )
        return caches, store, load

    def test_store_returns_false_when_budget_full(self, tmp_path):
        _, store, _ = self._loaded_handlers(tmp_path, budget_bytes=4096)
        store.budget.acquire(4096)  # saturate
        t0 = time.monotonic()
        accepted = store.transfer_async(
            1,
            (
                GPULoadStoreSpec(list(range(8))),
                TPUSharedStorageLoadStoreSpec([1, 2]),
            ),
        )
        assert accepted is False
        assert time.monotonic() - t0 < 1.0  # did not block
        assert 1 not in store._job_bytes  # no leaked accounting
        store.budget.release(4096)

    def test_load_returns_false_without_allocating(self, tmp_path):
        _, _, load = self._loaded_handlers(tmp_path, budget_bytes=4096)
        load.budget.acquire(4096)
        accepted = load.transfer_async(
            2,
            (
                TPUSharedStorageLoadStoreSpec([1, 2]),
                GPULoadStoreSpec(list(range(8))),
            ),
        )
        assert accepted is False
        assert 2 not in load._job_bytes
        assert 2 not in load._pending  # buffers were never allocated
        # in-flight is exactly the saturation we injected — the refused
        # job added nothing.
        assert load.budget.in_flight_bytes == 4096
        load.budget.release(4096)

    def test_rejected_transfer_succeeds_after_release(self, tmp_path):
        caches, store, load = self._loaded_handlers(
            tmp_path, budget_bytes=4096
        )
        original = caches["l0"].copy()
        ids = list(range(8))
        store.budget.acquire(4096)
        spec_pair = (
            GPULoadStoreSpec(ids),
            TPUSharedStorageLoadStoreSpec([1, 2]),
        )
        assert store.transfer_async(1, spec_pair) is False
        store.budget.release(4096)
        assert store.transfer_async(1, spec_pair) is True  # vLLM's retry
        store.wait({1})
        caches["l0"][...] = 0
        assert load.transfer_async(
            2, (TPUSharedStorageLoadStoreSpec([1, 2]), GPULoadStoreSpec(ids))
        )
        load.wait({2})
        np.testing.assert_array_equal(caches["l0"], original)


class TestStagingBudget:
    def test_acquire_release(self):
        budget = StagingBudget(100)
        assert budget.acquire(60)
        assert not budget.acquire(60, timeout=0.05)
        budget.release(60)
        assert budget.acquire(60)

    def test_try_acquire_never_blocks(self):
        budget = StagingBudget(100)
        assert budget.try_acquire(100)
        t0 = time.monotonic()
        assert not budget.try_acquire(1)
        assert time.monotonic() - t0 < 0.5
        budget.release(100)
        assert budget.try_acquire(1)

    def test_oversized_request_admitted_alone(self):
        budget = StagingBudget(10)
        assert budget.acquire(50)  # would deadlock if refused forever
        assert not budget.acquire(1, timeout=0.05)
        budget.release(50)
        assert budget.acquire(1)

    def test_burst_never_exceeds_budget(self, tmp_path):
        """A burst of stores from many threads must keep in-flight host
        bytes within max_staging_memory_gb at every sampled instant."""
        rng = np.random.default_rng(4)
        caches = {
            "l0": rng.standard_normal((64, 16, 2, 4)).astype(np.float32)
        }
        spec = spec_for(
            tmp_path,
            # Tiny budget: one file buffer is 2KB and each 4-file job
            # stages 8KB, so the 32KB budget holds ~4 jobs in flight.
            extra={"block_size": 64, "max_staging_memory_gb": 32 / (1 << 20)},
        )
        (_, _, store), _ = spec.get_handlers(caches, {"l0": StandardBackend})
        budget = store.budget
        assert budget.max_bytes == 32 * 1024

        violations = []
        stop = threading.Event()

        def sampler():
            while not stop.is_set():
                seen = budget.in_flight_bytes
                if seen > budget.max_bytes:
                    violations.append(seen)

        sampler_thread = threading.Thread(target=sampler)
        sampler_thread.start()

        def submit(job_id):
            ids = list(range(16))
            hashes = [job_id * 100 + i for i in range(4)]
            # transfer_async is non-blocking: False = budget full, retry
            # later (exactly what vLLM's worker does).
            while not store.transfer_async(
                job_id,
                (
                    GPULoadStoreSpec(ids),
                    TPUSharedStorageLoadStoreSpec(hashes),
                ),
            ):
                time.sleep(0.001)

        threads = [
            threading.Thread(target=submit, args=(j,)) for j in range(1, 9)
        ]
        for t in threads:
            t.start()
        deadline_jobs = set(range(1, 9))
        finished = set()
        while finished != deadline_jobs:
            for job_id, ok in store.get_finished():
                assert ok
                finished.add(job_id)
        for t in threads:
            t.join(timeout=10)
        stop.set()
        sampler_thread.join(timeout=5)
        assert not violations
        assert budget.in_flight_bytes == 0

    def test_thread_clamp_under_budget(self, tmp_path):
        """Staging-budget sizing semantics (decided; retires the seed
        xfail): the clamp unit is the EXACT block-major file buffer —
        blocks_per_file x kernel_blocks x Σ per-kernel-block view
        bytes — not a nominal per-file figure.  Here that is
        4 blocks x (16 x 2 x 4 floats) = 2048 bytes, and a budget of
        exactly one such buffer must clamp to a single I/O thread
        regardless of threads_per_chip or host core count
        (docs/configuration.md §8)."""
        caches = {
            "l0": np.zeros((64, 16, 2, 4), np.float32)
        }
        file_buffer_nbytes = 4 * (16 * 2 * 4) * 4
        spec = spec_for(
            tmp_path,
            extra={
                "block_size": 64,
                "threads_per_chip": 32,
                "max_staging_memory_gb": file_buffer_nbytes / (1 << 30),
            },
        )
        (_, _, store), _ = spec.get_handlers(caches, {"l0": StandardBackend})
        assert spec.file_buffer_nbytes == file_buffer_nbytes
        # The clamp unit and the runtime budget unit must agree: what
        # the budget charges per file at submit time is exactly one
        # clamp unit.
        assert store._job_nbytes([[0, 1, 2, 3]]) == file_buffer_nbytes
        assert store.engine.n_threads == 1
