"""What-if engine (obs/whatif.py; ISSUE 18).

The acceptance contract directly: time-compressed replay on the
virtual clock is deterministic (same capture + speed + arm => same
event interleaving digest and counters, single-index AND 3-replica
cluster modes), A/B replay reports a structured delta with a first
SLO-divergence point, the composition operators emit valid capture
artifacts the existing replay machinery accepts (scale/stretch
bit-exactly), the pinned reference capture is current, and the
inline-drain pool primitive it all schedules against matches the
worker path.
"""

from __future__ import annotations

import json
import os

import pytest

from llm_d_kv_cache_manager_tpu.kvcache.indexer import (
    Indexer,
    IndexerConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.token_processor import (
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvevents.events import (
    BlockStored,
    EventBatch,
)
from llm_d_kv_cache_manager_tpu.kvevents.pool import (
    Message,
    Pool,
    PoolConfig,
    _ShardQueue,
)
from llm_d_kv_cache_manager_tpu.obs import whatif
from llm_d_kv_cache_manager_tpu.obs.capture import (
    CaptureConfig,
    IncidentManager,
    InputCaptureRecorder,
    canonical_state,
    encode_capture,
    load_artifact,
)
from llm_d_kv_cache_manager_tpu.obs.replay import (
    CaptureMismatchError,
    _ReplayTokenizer,
    load_capture,
    replay_capture,
)
from llm_d_kv_cache_manager_tpu.obs.slo import envelope_states

REFERENCE = os.path.join(
    os.path.dirname(__file__), "testdata", "whatif_reference.cbor"
)
MODEL = "whatif-ref"
BLOCK = 4


@pytest.fixture(scope="module")
def reference():
    return load_capture(REFERENCE, allow_mismatch=True)


def _strip_wall(result):
    """The deterministic projection of a run result (wall-clock
    latencies/throughputs excluded by contract)."""
    events = {
        k: v
        for k, v in result["events"].items()
        if k != "per_sec_wall"
    }
    scores = {
        k: v
        for k, v in result["scores"].items()
        if k not in ("per_sec_wall", "latency_ms")
    }
    return {
        "events": events,
        "scores": scores,
        "digest": result["digest"],
        "mismatches": result["seq_classification_mismatches"],
        "timeline": result["slo"]["timeline"],
    }


class TestVirtualClockDeterminism:
    @pytest.mark.parametrize(
        "arm",
        [
            "shards=1",
            "shards=8",
            "mode=cluster,replicas=3",
            "depth=2,drain_rate=30",
            "backend=cost_aware,max_cost_mb=4",
        ],
    )
    def test_same_capture_same_arm_is_identical(self, reference, arm):
        cfg = whatif.WhatIfConfig(speed=8.0)
        spec = whatif.StackConfig.parse(arm)
        first = whatif.run_whatif(
            reference, spec, cfg, register=False
        )
        second = whatif.run_whatif(
            reference, spec, cfg, register=False
        )
        assert _strip_wall(first) == _strip_wall(second)

    def test_single_and_cluster_agree(self, reference):
        """The 3-replica cluster applies the same writes the single
        index does — deterministic counters and scores agree (digest
        folds scores + dispositions + canonical state, which the
        cluster dump normalizes to the single-index form)."""
        cfg = whatif.WhatIfConfig(speed=4.0)
        single = whatif.run_whatif(
            reference,
            whatif.StackConfig.parse(""),
            cfg,
            register=False,
        )
        cluster = whatif.run_whatif(
            reference,
            whatif.StackConfig.parse("mode=cluster,replicas=3"),
            cfg,
            register=False,
        )
        assert single["digest"] == cluster["digest"]
        assert (
            single["scores"]["hit_rate"] == cluster["scores"]["hit_rate"]
        )
        assert single["scores"]["recorded_parity"] == 1.0

    def test_speed_changes_schedule_not_measurements(self, reference):
        """With unbounded drain the apply schedule is
        arrival-synchronous, so compression changes checkpoint count
        but not hit rate or parity."""
        slow = whatif.run_whatif(
            reference,
            whatif.StackConfig.parse(""),
            whatif.WhatIfConfig(speed=2.0),
            register=False,
        )
        fast = whatif.run_whatif(
            reference,
            whatif.StackConfig.parse(""),
            whatif.WhatIfConfig(speed=10.0),
            register=False,
        )
        assert slow["scores"]["hit_rate"] == fast["scores"]["hit_rate"]
        assert slow["scores"]["recorded_parity"] == 1.0
        assert fast["virtual_span_s"] < slow["virtual_span_s"]
        assert fast["slo"]["checkpoints"] < slow["slo"]["checkpoints"]

    def test_finite_drain_rate_creates_real_backpressure(
        self, reference
    ):
        starved = whatif.run_whatif(
            reference,
            whatif.StackConfig.parse("depth=2,drain_rate=30"),
            whatif.WhatIfConfig(speed=8.0),
            register=False,
        )
        assert starved["events"]["shed"] > 0
        assert (
            starved["events"]["shed_reasons"].get("queue_full", 0) > 0
        )
        assert starved["events"]["applied"] < starved["events"]["offered"]
        assert starved["slo"]["final"]["whatif.event_shed"] == "violated"


class TestAbReplay:
    def test_shard_count_parity(self, reference):
        """shards=1 and shards=8 apply identical writes — ANY
        deterministic difference is a sharding bug, which is exactly
        what this A/B detects."""
        ab = whatif.run_ab(
            reference,
            whatif.StackConfig.parse("shards=1", name="s1"),
            whatif.StackConfig.parse("shards=8", name="s8"),
            whatif.WhatIfConfig(speed=8.0),
            register=False,
        )
        delta = ab["delta"]
        assert delta["digest_equal"]
        assert delta["hit_parity"] == 1.0
        assert delta["hit_rate"]["delta"] == 0.0
        assert delta["slo"]["first_divergence"] is None

    def test_flow_control_divergence(self, reference):
        ab = whatif.run_ab(
            reference,
            whatif.StackConfig.parse(
                "depth=2,drain_rate=30", name="tiny"
            ),
            whatif.StackConfig.parse("drain_rate=30", name="big"),
            whatif.WhatIfConfig(speed=8.0),
            register=False,
        )
        delta = ab["delta"]
        assert delta["shed"]["a"] > 0
        assert delta["shed"]["b"] == 0
        assert not delta["digest_equal"]
        divergence = delta["slo"]["first_divergence"]
        assert divergence is not None
        assert "whatif.event_shed" in divergence["slis"]
        assert divergence["a"]["whatif.event_shed"] != (
            divergence["b"]["whatif.event_shed"]
        )
        assert delta["slo"]["a_final"]["whatif.event_shed"] == "violated"
        assert delta["slo"]["b_final"]["whatif.event_shed"] == "healthy"

    def test_gate_headlines_shape(self, reference):
        ab = whatif.run_ab(
            reference,
            whatif.StackConfig.parse("shards=1", name="a"),
            whatif.StackConfig.parse("shards=8", name="b"),
            whatif.WhatIfConfig(speed=8.0),
            register=False,
        )
        headlines = whatif.gate_headlines(ab)
        assert set(headlines) == {
            "whatif.hit_rate",
            "whatif.recorded_parity",
            "whatif.ab_hit_parity",
        }
        assert headlines["whatif.recorded_parity"] == 1.0
        assert headlines["whatif.ab_hit_parity"] == 1.0
        assert 0.0 < headlines["whatif.hit_rate"] <= 1.0


class TestComposition:
    def test_scale_is_bit_exact_replayable(self, reference):
        scaled = whatif.scale_pods(reference, 2)
        art = load_capture(
            whatif.capture_to_bytes(scaled), allow_mismatch=True
        )
        assert art["meta"]["composed"] == "1"
        assert art["meta"]["compose_ops"] == "scale:2"
        report = replay_capture(art, mode="single")
        assert report.ok, report.to_dict()
        assert report.scores_compared > 0

    def test_stretch_is_bit_exact_replayable(self, reference):
        stretched = whatif.stretch(reference, 3.0)
        base_span = max(
            int(r[2]) for r in reference["records"]
        ) - min(int(r[2]) for r in reference["records"])
        new_span = max(
            int(r[2]) for r in stretched["records"]
        ) - min(int(r[2]) for r in stretched["records"])
        assert new_span == pytest.approx(base_span * 3, abs=2)
        report = replay_capture(
            load_capture(
                whatif.capture_to_bytes(stretched), allow_mismatch=True
            ),
            mode="single",
        )
        assert report.ok, report.to_dict()

    def test_splice_continues_seq_streams(self, reference):
        spliced = whatif.splice([reference, reference])
        art = load_capture(
            whatif.capture_to_bytes(spliced), allow_mismatch=True
        )
        assert len(art["records"]) == 2 * len(reference["records"])
        # Replaying the splice must classify every seq exactly as
        # recorded — the offset scheme continues each (pod, topic)
        # stream instead of restarting it.
        result = whatif.run_whatif(
            art,
            whatif.StackConfig.parse(""),
            whatif.WhatIfConfig(speed=10.0),
            register=False,
        )
        assert result["seq_classification_mismatches"] == 0
        assert result["events"]["offered"] == 2 * sum(
            1
            for r in reference["records"]
            if r[0] == 0 and r[8] is not None
        )

    def test_repeat_matches_splice(self, reference):
        assert (
            whatif.repeat(reference, 3)["records"]
            == whatif.splice([reference] * 3)["records"]
        )

    def test_interleave_renames_streams(self, reference):
        mixed = whatif.interleave([reference, reference])
        art = load_capture(
            whatif.capture_to_bytes(mixed), allow_mismatch=True
        )
        pods = {
            str(r[3]) for r in art["records"] if r[0] == 0
        }
        assert any(pod.endswith("~s1") for pod in pods)
        result = whatif.run_whatif(
            art,
            whatif.StackConfig.parse(""),
            whatif.WhatIfConfig(speed=10.0),
            register=False,
        )
        assert result["seq_classification_mismatches"] == 0

    def test_scale_expands_scores_and_filters(self, reference):
        scaled = whatif.scale_pods(reference, 2)
        for record in scaled["records"]:
            if record[0] != 1 or not record[6]:
                continue
            pods = [str(p) for p, _ in record[6]]
            base = [p for p in pods if not p.endswith("x1")]
            clones = [p for p in pods if p.endswith("x1")]
            assert len(base) == len(clones)
            break
        else:
            pytest.fail("no scored record with a score map")

    def test_incompatible_meta_refused(self, reference):
        other = dict(reference)
        other["meta"] = dict(reference["meta"], block_size="16")
        with pytest.raises(ValueError, match="block_size"):
            whatif.splice([reference, other])

    def test_encode_capture_round_trip(self):
        records = [
            [0, 1, 1000, "p", "t", "m", 1, 0, b"xx", "admitted"],
            [1, 2, 2000, "m", [1, 2], None, []],
        ]
        blob = encode_capture(
            records,
            fingerprint="fp",
            knobs=[["K", "V"]],
            created_us=7,
            window_s=3,
            max_bytes=9,
            truncated=["scores"],
            meta={"a": "b"},
            state=None,
        )
        art = load_artifact(blob)
        assert art["fingerprint"] == "fp"
        assert art["knobs"] == [("K", "V")]
        assert art["created_us"] == 7
        assert art["truncated"] == ["scores"]
        assert art["meta"] == {"a": "b"}
        assert art["records"] == records


class TestReferenceArtifact:
    def test_reference_capture_is_current(self):
        """The checked-in artifact must equal a fresh deterministic
        rebuild — a drift in hashing, capture framing, or the
        generator itself fails here with the regeneration command."""
        from hack.make_reference_capture import build_reference_capture

        with open(REFERENCE, "rb") as handle:
            disk = handle.read()
        assert disk == build_reference_capture(), (
            "tests/testdata/whatif_reference.cbor is stale; "
            "regenerate with: python hack/make_reference_capture.py "
            "(and refresh WHATIF_r01.json via the live headlines)"
        )

    def test_reference_ab_matches_recorded_baseline(self):
        """WHATIF_r01.json records deterministic measurements; the
        live engine must reproduce them exactly."""
        ab = whatif.reference_ab()
        live = whatif.gate_headlines(ab)
        with open(
            os.path.join(
                os.path.dirname(__file__), "..", "WHATIF_r01.json"
            )
        ) as handle:
            recorded = json.load(handle)["headlines"]
        assert live == recorded


def _tiny_stack():
    indexer = Indexer(
        IndexerConfig(
            token_processor_config=TokenProcessorConfig(
                block_size=BLOCK
            ),
            cache_stats=False,
        ),
        tokenizer=_ReplayTokenizer(),
    )
    indexer.run()
    return indexer


def _stored(hashes, tokens):
    return EventBatch(
        ts=1.0,
        events=[
            BlockStored(
                block_hashes=list(hashes),
                parent_block_hash=None,
                token_ids=list(tokens),
                block_size=BLOCK,
                medium="hbm",
            )
        ],
    ).encode()


def _messages(count=12):
    out = []
    for i in range(count):
        pod = f"p{i % 3}"
        out.append(
            Message(
                topic=f"kv@{pod}@{MODEL}",
                payload=_stored(
                    [10_000 + i], [i * BLOCK + j + 1 for j in range(BLOCK)]
                ),
                pod_identifier=pod,
                model_name=MODEL,
                seq=i // 3 + 1,
            )
        )
    return out


class TestProcessInline:
    def test_matches_worker_path(self):
        """Inline drain applies exactly what the started workers
        apply — same final canonical index state."""
        inline = _tiny_stack()
        workers = _tiny_stack()
        try:
            pool_inline = Pool(
                inline.kv_block_index,
                inline.token_processor,
                PoolConfig(concurrency=2),
            )
            for message in _messages():
                pool_inline.add_task(message)
            applied = pool_inline.process_inline()
            assert applied == 12
            assert pool_inline.backlog() == 0

            pool_workers = Pool(
                workers.kv_block_index,
                workers.token_processor,
                PoolConfig(concurrency=2),
            )
            pool_workers.start()
            for message in _messages():
                pool_workers.add_task(message)
            pool_workers.drain()
            pool_workers.shutdown()
            assert canonical_state(
                inline.kv_block_index
            ) == canonical_state(workers.kv_block_index)
        finally:
            inline.shutdown()
            workers.shutdown()

    def test_refuses_started_pool(self):
        stack = _tiny_stack()
        try:
            pool = Pool(
                stack.kv_block_index,
                stack.token_processor,
                PoolConfig(concurrency=1),
            )
            pool.start()
            try:
                with pytest.raises(RuntimeError, match="un-started"):
                    pool.process_inline()
            finally:
                pool.shutdown()
        finally:
            stack.shutdown()

    def test_limit_leaves_backlog(self):
        stack = _tiny_stack()
        try:
            pool = Pool(
                stack.kv_block_index,
                stack.token_processor,
                PoolConfig(concurrency=1, apply_batch_size=1),
            )
            for message in _messages():
                pool.add_task(message)
            assert pool.process_inline(5) == 5
            assert pool.backlog() == 7
            assert pool.process_inline() == 7
        finally:
            stack.shutdown()

    def test_try_get_batch_never_blocks(self):
        queue = _ShardQueue(max_depth=8, pod_budget=0, per_pod=False)
        assert queue.try_get_batch(4) == ([], {})


class TestConfigAndRegistry:
    def test_parse_rejects_unknown_knob(self):
        with pytest.raises(ValueError, match="unknown arm knob"):
            whatif.StackConfig.parse("bogus=1")

    def test_parse_rejects_cluster_cost_aware(self):
        with pytest.raises(ValueError, match="cluster"):
            whatif.StackConfig.parse("mode=cluster,backend=cost_aware")

    def test_registry_bounded_newest_first(self):
        registry = whatif.WhatIfRegistry(keep=2)
        for i in range(4):
            registry.add(
                {
                    "kind": "run",
                    "arm": f"a{i}",
                    "events": {"offered": i},
                    "scores": {},
                    "digest": str(i),
                }
            )
        listed = registry.list()
        assert len(listed) == 2
        assert [row["arm"] for row in listed] == ["a3", "a2"]
        assert registry.status()["results"] == 2
        full = registry.list(full=True)
        assert full[0]["events"] == {"offered": 3}

    def test_envelope_states_shape(self):
        payload = {
            "state": "degraded",
            "slis": {
                "x": {"state": "violated"},
                "y": {"state": "healthy"},
            },
        }
        assert envelope_states(payload) == {
            "overall": "degraded",
            "x": "violated",
            "y": "healthy",
        }

    def test_resolve_capture_source_bundle_dir(self, tmp_path):
        bundle = tmp_path / "inc-x"
        bundle.mkdir()
        with pytest.raises(FileNotFoundError, match="capture.cbor"):
            whatif.resolve_capture_source(str(bundle))
        (bundle / "capture.cbor").write_bytes(b"x")
        assert whatif.resolve_capture_source(str(bundle)) == str(
            bundle / "capture.cbor"
        )


class TestCli:
    def test_compose_then_run(self, tmp_path, capsys):
        out = tmp_path / "storm.cbor"
        rc = whatif.main(
            [
                "compose",
                str(out),
                REFERENCE,
                "--op",
                "scale:2",
                "--op",
                "stretch:0.5",
            ]
        )
        assert rc == 0
        composed = load_capture(str(out), allow_mismatch=True)
        assert composed["meta"]["compose_ops"] == "scale:2+stretch:0.5"
        rc = whatif.main(
            [
                "run",
                str(out),
                "--arm",
                "shards=8",
                "--speed",
                "10",
                "--json",
                str(tmp_path / "result.json"),
            ]
        )
        assert rc == 0
        with open(tmp_path / "result.json") as handle:
            result = json.load(handle)
        assert result["kind"] == "run"
        assert result["scores"]["total"] > 0

    def test_ab_cli(self, tmp_path):
        rc = whatif.main(
            [
                "ab",
                REFERENCE,
                "--a",
                "shards=1",
                "--b",
                "shards=8",
                "--speed",
                "10",
                "--json",
                str(tmp_path / "ab.json"),
            ]
        )
        assert rc == 0
        with open(tmp_path / "ab.json") as handle:
            ab = json.load(handle)
        assert ab["delta"]["digest_equal"] is True


class TestMismatchErrorNamesArtifact:
    def test_path_and_short_hash_in_message(self):
        with pytest.raises(CaptureMismatchError) as excinfo:
            load_capture(REFERENCE)
        message = str(excinfo.value)
        assert "whatif_reference.cbor" in message
        assert "whatif-re" in message  # fingerprint short-hash prefix
        assert excinfo.value.source == REFERENCE

    def test_bytes_source_still_reports(self, reference):
        blob = whatif.capture_to_bytes(reference)
        with pytest.raises(CaptureMismatchError) as excinfo:
            load_capture(blob)
        assert excinfo.value.source is None


class TestIncidentDetail:
    def _manager(self, tmp_path):
        recorder = InputCaptureRecorder(
            CaptureConfig(window_s=3600.0, max_bytes=1 << 20),
            meta={"block_size": BLOCK, "hash_seed": "", "model": MODEL},
        )
        recorder.record_kvevents_batch(
            [("p", "t", MODEL, 1, 0, b"xx", "admitted")]
        )
        return IncidentManager(
            str(tmp_path),
            capture=recorder,
            sources={"slo": lambda: {"ok": True}},
            min_interval_s=0.0,
        )

    def test_detail_lists_manifest_and_inventory(self, tmp_path):
        manager = self._manager(tmp_path)
        manifest = manager.trigger("test", force=True)
        detail = manager.detail(manifest["id"])
        assert detail["id"] == manifest["id"]
        assert detail["manifest"]["reason"] == "test"
        files = {row["file"] for row in detail["inventory"]}
        assert "manifest.json" in files
        assert "capture.cbor" in files
        assert all(row["bytes"] > 0 for row in detail["inventory"])

    def test_detail_unknown_and_traversal(self, tmp_path):
        manager = self._manager(tmp_path)
        assert manager.detail("inc-nope") is None
        assert manager.detail("../etc") is None
        assert manager.detail("inc-../../etc") is None
