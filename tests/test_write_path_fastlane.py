"""Write-path fast-lane parity + property tests (docs/event-plane.md).

The fast lane's three accelerators each keep a straight path as a
parity oracle, and these tests pin the equivalences:

* lock-free pre-decode (``KVEVENTS_LOCKFREE_DECODE``) ≡ straight
  in-worker decode — same index state and same per-pod journal record
  streams under an 8-thread mixed add/evict/poison/resync storm;
* publisher-side coalescing (``KVEVENTS_COALESCE_EVENTS``) ≡ the
  uncoalesced stream — same index state, same journal records, same
  seq/gap/restart classification, fewer wire messages, contiguous
  seqs;
* the per-worker digest memo (``KVEVENTS_DIGEST_MEMO``) ≡ memoless
  hashing (request keys are pure functions of parent+model+tokens);
* the O(1) shed-victim pick (depth buckets) always sheds a pod whose
  lane is the longest — the same fairness contract the old O(lanes)
  ``max`` scan enforced;
* batched enqueue (``Pool.add_tasks``) ≡ message-at-a-time
  ``add_task``.

Plus the replica-local ingestion slicer: deterministic disjoint/
complete pod partition, ring-bump re-slice with takeover resync, and
the membership listener wiring.
"""

import random
import struct
import threading

import pytest

from llm_d_kv_cache_manager_tpu.cluster.ingest import (
    ReplicaIngestor,
    pod_owner,
    slice_pods,
)
from llm_d_kv_cache_manager_tpu.cluster.membership import (
    ClusterMembership,
)
from llm_d_kv_cache_manager_tpu.cluster.ring import HashRing
from llm_d_kv_cache_manager_tpu.kvcache.kvblock import (
    ChunkedTokenDatabase,
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.in_memory import (
    InMemoryIndex,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import (
    InMemoryIndexConfig,
)
from llm_d_kv_cache_manager_tpu.kvevents.events import (
    BlockRemoved,
    BlockStored,
    EventBatch,
)
from llm_d_kv_cache_manager_tpu.kvevents.pool import (
    Message,
    Pool,
    PoolConfig,
    ResyncJob,
    _ShardQueue,
)
from llm_d_kv_cache_manager_tpu.kvevents.publisher import Publisher
from llm_d_kv_cache_manager_tpu.kvevents.zmq_subscriber import (
    TopicSeqTracker,
    parse_event_message,
)

MODEL = "m"
BLOCK = 4


class RecordingJournal:
    """Journal double capturing applied-op records (thread-safe)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.records = []

    def record_add(self, pod, seq, engine_keys, request_keys, entries):
        with self._lock:
            self.records.append(
                (
                    "add",
                    pod,
                    tuple(engine_keys),
                    tuple(request_keys),
                    tuple(
                        (e.pod_identifier, e.device_tier) for e in entries
                    ),
                )
            )

    def record_evict(self, pod, seq, engine_keys, entries):
        with self._lock:
            self.records.append(("evict", pod, tuple(engine_keys)))

    def record_purge(self, pod, seq=0):
        with self._lock:
            self.records.append(("purge", pod))

    def per_pod(self, pod):
        with self._lock:
            return [r for r in self.records if r[1] == pod]


def make_pool(journal=None, **cfg):
    index = InMemoryIndex(InMemoryIndexConfig(size=100_000))
    db = ChunkedTokenDatabase(TokenProcessorConfig(block_size=BLOCK))
    pool = Pool(index, db, PoolConfig(**cfg), journal=journal)
    return pool, index


def pod_stream(rng, pod, n_events, token_offset=0):
    """A valid per-pod event stream: chained BlockStored runs with
    interleaved removals and the occasional poison payload, as
    ``[(payload_bytes, kind), ...]``.

    ``token_offset`` keeps token (and therefore request-key) spaces
    DISJOINT across pods: a request key shared by two pods makes the
    engine-mapping cleanup order depend on cross-pod thread
    scheduling — inherent PUB/SUB raciness that would poison a parity
    oracle comparing two separately-scheduled runs."""
    messages = []
    base = rng.randrange(1, 1 << 20) * 1000
    chain_tail = None
    stored = []
    for i in range(n_events):
        roll = rng.random()
        if roll < 0.08:
            messages.append((b"\x01garbage", "poison"))
            continue
        if roll < 0.25 and stored:
            victim = stored.pop(rng.randrange(len(stored)))
            event = BlockRemoved(block_hashes=[victim])
            if victim == chain_tail:
                chain_tail = None
            messages.append(
                (EventBatch(ts=0.0, events=[event]).encode(), "removed")
            )
            continue
        n_blocks = rng.randrange(1, 3)
        hashes = [base + 10 * i + j for j in range(n_blocks)]
        tokens = [
            (base + 17 * i + j) % 30000 + 1 + token_offset
            for j in range(BLOCK * n_blocks)
        ]
        event = BlockStored(
            block_hashes=hashes,
            parent_block_hash=chain_tail if rng.random() < 0.5 else None,
            token_ids=tokens,
            block_size=BLOCK,
            medium=rng.choice([None, "hbm", "host"]),
        )
        chain_tail = hashes[-1]
        stored.extend(hashes)
        messages.append(
            (EventBatch(ts=0.0, events=[event]).encode(), "stored")
        )
    return messages


def run_storm(pool, journal, streams, resync_for=None, threads=8):
    """Drive per-pod streams from ``threads`` worker threads (each
    thread owns whole pods, preserving per-pod publish order), with an
    optional mid-stream resync command per pod."""
    pods = sorted(streams)
    pool.start()
    done_events = []

    def run_pod(pod, messages):
        for i, (payload, _kind) in enumerate(messages):
            pool.add_task(
                Message(
                    topic=f"kv@{pod}@{MODEL}",
                    payload=payload,
                    pod_identifier=pod,
                    model_name=MODEL,
                    seq=i + 1,
                )
            )
            if resync_for and pod in resync_for and i == len(messages) // 2:
                job, done = resync_for[pod]()
                done_events.append(done)
                pool.enqueue_resync(job)

    def worker(worker_pods):
        for pod in worker_pods:
            run_pod(pod, streams[pod])

    thread_objs = [
        threading.Thread(target=worker, args=(pods[t::threads],))
        for t in range(threads)
    ]
    for t in thread_objs:
        t.start()
    for t in thread_objs:
        t.join()
    pool.drain()
    for done in done_events:
        assert done.wait(10), "resync job never reported"
    pool.shutdown()


def index_state(index):
    block_entries, engine_map = index.dump_entries()
    return (
        sorted(
            (key, tuple(sorted((e.pod_identifier, e.device_tier) for e in entries)))
            for key, entries in block_entries
        ),
        sorted(engine_map),
    )


class TestLockfreeDecodeParity:
    @pytest.mark.parametrize("seed", [1, 7])
    def test_storm_parity_lockfree_vs_straight(self, seed):
        rng = random.Random(seed)
        pods = [f"storm-{i}" for i in range(16)]
        streams = {
            pod: pod_stream(rng, pod, 40, token_offset=30000 * i)
            for i, pod in enumerate(pods)
        }

        # A mid-stream resync for a quarter of the pods: purge + a
        # fixed one-block inventory, identical on both sides.  The
        # inventory hash is pod-unique and deterministic — a shared or
        # seed-dependent key would make cross-pod outcomes depend on
        # thread scheduling and poison the parity oracle.
        def resync_factory(pod):
            pod_index = int(pod.rsplit("-", 1)[1])

            def build():
                done = threading.Event()
                job = ResyncJob(
                    pod_identifier=pod,
                    model_name=MODEL,
                    events=[
                        BlockStored(
                            block_hashes=[99_000_000 + pod_index],
                            parent_block_hash=None,
                            # Pod-unique token chain (same reason as
                            # pod_stream's token_offset): a request
                            # key shared across pods races cross-pod.
                            token_ids=[
                                1_000_000 + pod_index * BLOCK + j
                                for j in range(1, BLOCK + 1)
                            ],
                            block_size=BLOCK,
                        )
                    ],
                    on_done=lambda j, ok, purged, detail: done.set(),
                )
                return job, done

            return build

        resync_for = {pod: resync_factory(pod) for pod in pods[::4]}

        states = {}
        journals = {}
        for mode, cfg in (
            ("straight", dict(lockfree_decode=False, digest_memo=0)),
            ("lockfree", dict(lockfree_decode=True, digest_memo=64)),
        ):
            journal = RecordingJournal()
            pool, index = make_pool(journal=journal, concurrency=4, **cfg)
            run_storm(pool, journal, streams, resync_for=resync_for)
            states[mode] = index_state(index)
            journals[mode] = journal
        assert states["straight"] == states["lockfree"]
        for pod in pods:
            assert journals["straight"].per_pod(pod) == journals[
                "lockfree"
            ].per_pod(pod), f"journal drift for {pod}"

    def test_predecode_marks_poison_and_worker_skips(self):
        pool, index = make_pool(concurrency=1, lockfree_decode=True)
        message = Message(
            topic=f"kv@p@{MODEL}",
            payload=b"\x01garbage",
            pod_identifier="p",
            model_name=MODEL,
        )
        pool.start()
        pool.add_tasks([message])
        pool.drain()
        pool.shutdown()
        assert message.decoded is not None  # the failure sentinel
        assert index.dump_entries() == ([], [])

    def test_predecode_happens_before_queue(self):
        pool, _index = make_pool(concurrency=1, lockfree_decode=True)
        payload = EventBatch(
            ts=0.0,
            events=[
                BlockStored(
                    block_hashes=[1],
                    parent_block_hash=None,
                    token_ids=list(range(1, BLOCK + 1)),
                    block_size=BLOCK,
                )
            ],
        ).encode()
        message = Message(
            topic=f"kv@p@{MODEL}",
            payload=payload,
            pod_identifier="p",
            model_name=MODEL,
        )
        # Pool not started: workers cannot have decoded it.
        pool.add_tasks([message])
        assert isinstance(message.decoded, EventBatch)
        stats = pool.stage_stats()
        assert stats["decode_msgs"] == 1 and stats["apply_msgs"] == 0
        pool.start()
        pool.drain()
        assert pool.stage_stats()["apply_msgs"] == 1
        pool.shutdown()

    def test_memoryview_payload_decodes(self):
        pool, index = make_pool(concurrency=1, lockfree_decode=True)
        payload = EventBatch(
            ts=0.0,
            events=[
                BlockStored(
                    block_hashes=[5],
                    parent_block_hash=None,
                    token_ids=list(range(1, BLOCK + 1)),
                    block_size=BLOCK,
                )
            ],
        ).encode()
        pool.start()
        pool.add_tasks(
            [
                Message(
                    topic=f"kv@p@{MODEL}",
                    payload=memoryview(payload),
                    pod_identifier="p",
                    model_name=MODEL,
                )
            ]
        )
        pool.drain()
        pool.shutdown()
        block_entries, engine_map = index.dump_entries()
        assert len(block_entries) == 1 and len(engine_map) == 1


class TestDigestMemoParity:
    def test_repeated_chains_identical_state(self):
        rng = random.Random(3)
        pods = [f"memo-{i}" for i in range(6)]
        # Heavy repetition WITHIN each pod (its own stream replayed
        # three times): memo hits without cross-pod key sharing —
        # shared engine keys would make evict/store interleaving
        # across pods schedule-dependent and break the oracle.
        streams = {}
        for i, pod in enumerate(pods):
            stream = pod_stream(rng, pod, 20, token_offset=30000 * i)
            streams[pod] = stream * 3
        states = {}
        for mode, cfg in (
            ("memo", dict(digest_memo=32)),
            ("memoless", dict(digest_memo=0)),
        ):
            pool, index = make_pool(concurrency=2, **cfg)
            run_storm(pool, None, streams, threads=3)
            states[mode] = index_state(index)
        assert states["memo"] == states["memoless"]


class TestShedVictimProperty:
    def test_overflow_always_sheds_a_longest_lane(self):
        rng = random.Random(11)
        q = _ShardQueue(max_depth=32, pod_budget=1000, per_pod=True)
        pods = [f"s{i}" for i in range(9)]
        for step in range(3000):
            pod = rng.choice(pods)
            depths_before = q.lane_depths()
            shed, _depth = q.put(
                Message(
                    topic="t",
                    payload=b"",
                    pod_identifier=pod,
                    model_name=MODEL,
                    seq=step,
                )
            )
            for victim, reason in shed:
                assert reason == "queue_full"
                assert depths_before[victim.pod_identifier] == max(
                    depths_before.values()
                )
            if rng.random() < 0.2:
                batch, _closed, _depths = q.get_batch(rng.randrange(1, 8))
                assert batch
        # Buckets stay consistent with the depth map throughout.
        depths = q.lane_depths()
        assert sum(depths.values()) == q.qsize()

    def test_budget_shed_still_self_targets(self):
        q = _ShardQueue(max_depth=1000, pod_budget=3, per_pod=True)
        for i in range(10):
            shed, _ = q.put(
                Message(
                    topic="t",
                    payload=b"",
                    pod_identifier="greedy",
                    model_name=MODEL,
                    seq=i,
                )
            )
            for victim, reason in shed:
                assert reason == "pod_budget"
                assert victim.pod_identifier == "greedy"
        assert q.lane_depths() == {"greedy": 3}


class TestBatchedEnqueue:
    def test_add_tasks_equivalent_to_add_task(self):
        rng = random.Random(5)
        pods = [f"b{i}" for i in range(8)]
        streams = {
            pod: pod_stream(rng, pod, 25, token_offset=30000 * i)
            for i, pod in enumerate(pods)
        }
        states = {}
        for mode in ("single", "batched"):
            pool, index = make_pool(concurrency=2, lockfree_decode=True)
            pool.start()
            if mode == "single":
                for pod, stream in streams.items():
                    for i, (payload, _kind) in enumerate(stream):
                        pool.add_task(
                            Message(
                                topic=f"kv@{pod}@{MODEL}",
                                payload=payload,
                                pod_identifier=pod,
                                model_name=MODEL,
                                seq=i,
                            )
                        )
            else:
                burst = []
                for pod, stream in streams.items():
                    for i, (payload, _kind) in enumerate(stream):
                        burst.append(
                            Message(
                                topic=f"kv@{pod}@{MODEL}",
                                payload=payload,
                                pod_identifier=pod,
                                model_name=MODEL,
                                seq=i,
                            )
                        )
                        if len(burst) == 16:
                            pool.add_tasks(burst)
                            burst = []
                pool.add_tasks(burst)
            pool.drain()
            pool.shutdown()
            states[mode] = index_state(index)
        assert states["single"] == states["batched"]

    def test_put_batch_shutdown_rejects_all(self):
        q = _ShardQueue(max_depth=8, pod_budget=8, per_pod=True)
        q.close()
        msgs = [
            Message(
                topic="t",
                payload=b"",
                pod_identifier=f"p{i}",
                model_name=MODEL,
            )
            for i in range(3)
        ]
        shed, depths = q.put_batch(msgs)
        assert depths == {}
        assert [reason for _m, reason in shed] == ["shutdown"] * 3


def drain_sub(sock, tracker, pod, limit=10_000):
    """Drain everything currently queued on an inproc SUB socket."""
    import zmq

    out = []
    for _ in range(limit):
        try:
            parts = sock.recv_multipart(zmq.NOBLOCK)
        except zmq.Again:
            break
        message = parse_event_message(
            parts, endpoint="e", pod_identifier=pod, tracker=tracker
        )
        if message is not None:
            out.append(message)
    return out


class TestPublisherCoalescing:
    def _publish_stream(self, coalesce_events, events_per_call, seed=9):
        import zmq

        context = zmq.Context.instance()
        pod = f"co-{coalesce_events}-{seed}"
        pub = Publisher(
            "inproc://" + pod,
            pod,
            MODEL,
            context=context,
            coalesce_events=coalesce_events,
            coalesce_ms=60_000.0,  # only size/flush triggers in tests
        )
        sub = context.socket(zmq.SUB)
        sub.setsockopt(zmq.LINGER, 0)
        sub.setsockopt(zmq.SUBSCRIBE, b"")
        sub.connect("inproc://" + pod)
        import time as _time

        _time.sleep(0.05)  # inproc join
        rng = random.Random(seed)
        # Random event objects (valid chains within one publisher).
        events = []
        chain_tail = None
        base = 5000
        for i in range(40):
            if rng.random() < 0.25 and events:
                events.append(BlockRemoved(block_hashes=[base + i - 1]))
                continue
            stored = BlockStored(
                block_hashes=[base + i],
                parent_block_hash=chain_tail,
                token_ids=[
                    (base + i * 7 + j) % 3000 + 1 for j in range(BLOCK)
                ],
                block_size=BLOCK,
            )
            chain_tail = base + i
            events.append(stored)
        calls = []
        i = 0
        while i < len(events):
            n = min(events_per_call, len(events) - i)
            calls.append(events[i : i + n])
            i += n
        # A forced seq skip mid-stream must classify identically.
        for j, call in enumerate(calls):
            if j == len(calls) // 2:
                pub.flush()
                pub.advance_seq(3)
            pub.publish(*call)
        pub.flush()
        tracker = TopicSeqTracker()
        messages = drain_sub(sub, tracker, pod)
        pub.close()
        sub.close()
        return messages, tracker, events

    def apply_messages(self, messages, journal):
        pool, index = make_pool(journal=journal, concurrency=1)
        pool.start()
        pool.add_tasks(messages)
        pool.drain()
        pool.shutdown()
        return index_state(index)

    def test_coalesced_equals_uncoalesced(self):
        plain_msgs, plain_tracker, plain_events = self._publish_stream(
            coalesce_events=0, events_per_call=1
        )
        co_msgs, co_tracker, co_events = self._publish_stream(
            coalesce_events=8, events_per_call=1
        )
        assert [e.to_tagged_union() for e in plain_events] == [
            e.to_tagged_union() for e in co_events
        ]
        # Fewer wire messages, same events, same gap classification.
        assert len(co_msgs) < len(plain_msgs)
        assert plain_tracker.gap_count == co_tracker.gap_count == 3
        assert plain_tracker.restart_count == co_tracker.restart_count == 0

        plain_journal = RecordingJournal()
        co_journal = RecordingJournal()
        plain_state = self.apply_messages(plain_msgs, plain_journal)
        # The coalesced pod id differs; rewrite pod identity so both
        # streams index the same pod.
        pod = plain_msgs[0].pod_identifier
        for message in co_msgs:
            message.pod_identifier = pod
            message.topic = plain_msgs[0].topic
        co_state = self.apply_messages(co_msgs, co_journal)
        assert plain_state == co_state
        assert [
            (op, keys) for op, _pod, keys, *rest in plain_journal.records
        ] == [(op, keys) for op, _pod, keys, *rest in co_journal.records]

    def test_buffered_publish_returns_none_then_flush_seq(self):
        import zmq

        pub = Publisher(
            "inproc://co-flush",
            "co-flush",
            MODEL,
            context=zmq.Context.instance(),
            coalesce_events=10,
            coalesce_ms=60_000.0,
        )
        stored = BlockStored(
            block_hashes=[1],
            parent_block_hash=None,
            token_ids=[1, 2, 3, 4],
            block_size=BLOCK,
        )
        assert pub.publish(stored) is None
        assert pub.publish(stored) is None
        seq = pub.flush()
        assert seq == 1
        assert pub.flush() is None
        # Size trigger: the 10th event flushes inline.
        seqs = [pub.publish(stored) for _ in range(10)]
        assert seqs[:-1] == [None] * 9 and seqs[-1] == 2
        pub.close()

    def test_close_flushes_buffer(self):
        import zmq

        context = zmq.Context.instance()
        pub = Publisher(
            "inproc://co-close",
            "co-close",
            MODEL,
            context=context,
            coalesce_events=100,
            coalesce_ms=60_000.0,
        )
        sub = context.socket(zmq.SUB)
        sub.setsockopt(zmq.LINGER, 0)
        sub.setsockopt(zmq.SUBSCRIBE, b"")
        sub.connect("inproc://co-close")
        import time as _time

        _time.sleep(0.05)
        stored = BlockStored(
            block_hashes=[1],
            parent_block_hash=None,
            token_ids=[1, 2, 3, 4],
            block_size=BLOCK,
        )
        assert pub.publish(stored) is None
        pub.close()
        messages = drain_sub(sub, TopicSeqTracker(), "co-close")
        sub.close()
        assert len(messages) == 1

    def test_concurrent_coalesced_publish_keeps_seqs_ordered(self):
        import zmq

        context = zmq.Context.instance()
        pub = Publisher(
            "inproc://co-mt",
            "co-mt",
            MODEL,
            context=context,
            coalesce_events=4,
            coalesce_ms=60_000.0,
        )
        sub = context.socket(zmq.SUB)
        sub.setsockopt(zmq.LINGER, 0)
        sub.setsockopt(zmq.SUBSCRIBE, b"")
        sub.connect("inproc://co-mt")
        import time as _time

        _time.sleep(0.05)
        stored = BlockStored(
            block_hashes=[1],
            parent_block_hash=None,
            token_ids=[1, 2, 3, 4],
            block_size=BLOCK,
        )

        def spam():
            for _ in range(100):
                pub.publish(stored)

        threads = [threading.Thread(target=spam) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        pub.flush()
        _time.sleep(0.05)
        seqs = []
        for _ in range(10_000):
            try:
                parts = sub.recv_multipart(zmq.NOBLOCK)
            except zmq.Again:
                break
            seqs.append(struct.unpack(">Q", parts[1])[0])
        pub.close()
        sub.close()
        assert seqs == sorted(seqs)
        assert len(set(seqs)) == len(seqs)
        # 400 events in batches of 4 -> 100 wire messages (+ remainder).
        assert seqs and seqs[-1] == len(seqs)


class FakeManager:
    def __init__(self):
        self.active = {}
        self.calls = []

    def ensure_subscriber(self, pod, endpoint, topic_filter=None):
        fresh = self.active.get(pod) != (endpoint, topic_filter)
        self.active[pod] = (endpoint, topic_filter)
        self.calls.append(("ensure", pod))
        return fresh

    def remove_subscriber(self, pod):
        self.calls.append(("remove", pod))
        return self.active.pop(pod, None) is not None


class FakeResync:
    def __init__(self):
        self.requested = []

    def request_resync(self, pod, model_name=""):
        self.requested.append(pod)
        return True


class TestReplicaIngestor:
    def test_partition_is_disjoint_and_complete(self):
        ring = HashRing(["r0", "r1", "r2"])
        pods = [f"pod-{i}" for i in range(60)]
        slices = {
            r: set(slice_pods(ring, r, pods)) for r in ring.members
        }
        union = set().union(*slices.values())
        assert union == set(pods)
        total = sum(len(s) for s in slices.values())
        assert total == len(pods)
        # Deterministic across calls and consistent with pod_owner.
        for r, owned in slices.items():
            for pod in owned:
                assert pod_owner(ring, pod) == r

    def test_subscribes_only_owned_slice(self):
        ring = HashRing(["r0", "r1", "r2"])
        pods = [f"pod-{i}" for i in range(30)]
        manager = FakeManager()
        ingestor = ReplicaIngestor("r0", manager, ring=ring)
        for pod in pods:
            ingestor.ensure_subscriber(pod, f"tcp://{pod}:5557")
        assert set(manager.active) == set(slice_pods(ring, "r0", pods))
        assert ingestor.known_pods() == sorted(pods)
        assert ingestor.owned_pods() == sorted(manager.active)

    def test_ring_bump_takes_over_and_resyncs(self):
        ring = HashRing(["r0", "r1", "r2"])
        pods = [f"pod-{i}" for i in range(40)]
        manager = FakeManager()
        resync = FakeResync()
        ingestor = ReplicaIngestor(
            "r0", manager, ring=ring, resync=resync
        )
        for pod in pods:
            ingestor.ensure_subscriber(pod, f"tcp://{pod}:5557")
        before = set(manager.active)
        shrunk = ring.without("r1")
        ingestor.apply_ring(shrunk)
        after = set(manager.active)
        gained = after - before
        # Exactly r1's pods that now rendezvous to r0, all resynced.
        expected = {
            pod
            for pod in pods
            if pod_owner(ring, pod) == "r1"
            and pod_owner(shrunk, pod) == "r0"
        }
        assert gained == expected
        assert set(resync.requested) == expected
        # Rejoin: the reclaimed pods detach again, no extra resyncs.
        ingestor.apply_ring(ring.without("r1").with_member("r1"))
        assert set(manager.active) == before
        assert set(resync.requested) == expected

    def test_membership_listener_wiring(self):
        class DummyTransport:
            def call(self, method, args):
                return "ok"

        membership = ClusterMembership(
            {r: DummyTransport() for r in ("r0", "r1", "r2")}
        )
        manager = FakeManager()
        ingestor = ReplicaIngestor(
            "r0", manager, membership=membership
        )
        pods = [f"pod-{i}" for i in range(30)]
        for pod in pods:
            ingestor.ensure_subscriber(pod, f"tcp://{pod}:5557")
        before = set(manager.active)
        assert membership.mark_dead("r1", "test")
        assert set(manager.active) >= before
        assert ingestor.status()["reslices"] == 1
        assert membership.mark_alive("r1")
        assert set(manager.active) == before
        assert ingestor.status()["reslices"] == 2

    def test_stale_ring_notification_ignored(self):
        # Membership notifies listeners outside its lock, so two
        # near-simultaneous failovers can deliver rings out of order;
        # the older ring must not overwrite the newer slicing.
        ring = HashRing(["r0", "r1", "r2"])
        manager = FakeManager()
        ingestor = ReplicaIngestor("r0", manager, ring=ring)
        pods = [f"pod-{i}" for i in range(30)]
        for pod in pods:
            ingestor.ensure_subscriber(pod, "tcp://x:1")
        newer = ring.without("r1").without("r2")  # v2: r0 owns all
        ingestor.apply_ring(newer)
        assert set(manager.active) == set(pods)
        stale = ring.without("r2")  # v1, delivered late
        ingestor.apply_ring(stale)
        assert set(manager.active) == set(pods)
        assert ingestor.status()["ring_version"] == 2
        assert ingestor.status()["reslices"] == 1

    def test_active_pods_reports_known_fleet_for_pruning(self):
        # The reconciler prunes departed pods by diffing active_pods()
        # against its list response: the ingestor must report the
        # KNOWN fleet, not just the owned slice, or a departed
        # unowned pod would be resubscribed as a ghost on takeover.
        ring = HashRing(["r0", "r1", "r2"])
        manager = FakeManager()
        ingestor = ReplicaIngestor("r0", manager, ring=ring)
        pods = [f"pod-{i}" for i in range(12)]
        for pod in pods:
            ingestor.ensure_subscriber(pod, "tcp://x:1")
        assert ingestor.active_pods() == sorted(pods)
        gone = pods[0]
        ingestor.remove_subscriber(gone)
        assert gone not in ingestor.active_pods()
        # A later takeover must not resurrect it.
        ingestor.apply_ring(ring.without("r1"))
        assert gone not in manager.active

    def test_unowned_pod_rejected_and_stale_channel_dropped(self):
        ring = HashRing(["r0", "r1"])
        manager = FakeManager()
        ingestor = ReplicaIngestor("r0", manager, ring=ring)
        pods = [f"pod-{i}" for i in range(20)]
        mine = slice_pods(ring, "r0", pods)
        other = [p for p in pods if p not in mine]
        assert other, "need at least one foreign pod"
        for pod in pods:
            ingestor.ensure_subscriber(pod, "tcp://x:1")
        # A re-announce of a foreign pod must not subscribe it.
        assert ingestor.ensure_subscriber(other[0], "tcp://x:2") is False
        assert other[0] not in manager.active


class TestCaptureParity:
    """ISSUE 15 satellite: adversarial write paths — lock-free decode,
    the coalesced publisher, per-pod shedding, and resync jobs — must
    record the same input-capture disposition stream as the straight
    path (obs/capture.py records post shed decision; the stream is
    what obs/replay.py re-drives)."""

    @staticmethod
    def _recorder():
        from llm_d_kv_cache_manager_tpu.obs.capture import (
            CaptureConfig,
            InputCaptureRecorder,
        )

        return InputCaptureRecorder(
            CaptureConfig(window_s=3600.0, max_bytes=64 << 20),
            meta={"block_size": BLOCK, "hash_seed": ""},
        )

    @staticmethod
    def _per_pod_stream(recorder):
        """pod -> [(topic, seq, seq_gap, payload, disposition), ...]
        in capture (global seq) order."""
        from llm_d_kv_cache_manager_tpu.obs.capture import (
            load_artifact,
        )

        out = {}
        for record in load_artifact(recorder.dump_bytes())["records"]:
            if record[0] != 0:
                continue
            out.setdefault(record[3], []).append(
                (record[4], record[6], record[7], record[8], record[9])
            )
        return out

    @pytest.mark.parametrize("seed", [3, 19])
    def test_lockfree_equals_straight_disposition_stream(self, seed):
        """Same seeded per-pod streams (stores, removals, poison
        pills) through the lock-free pre-decode path and the straight
        in-worker path: identical per-pod capture subsequences —
        topic, seq, payload bytes, disposition."""
        rng = random.Random(seed)
        pods = [f"cap-{i}" for i in range(8)]
        streams = {
            pod: pod_stream(rng, pod, 30, token_offset=30000 * i)
            for i, pod in enumerate(pods)
        }
        captured = {}
        for lockfree in (True, False):
            recorder = self._recorder()
            pool, _index = make_pool(
                concurrency=2, lockfree_decode=lockfree
            )
            pool.set_capture(recorder)
            run_storm(pool, None, streams, threads=4)
            captured[lockfree] = self._per_pod_stream(recorder)
        assert captured[True] == captured[False]
        # Poison pills are admitted ingress on both sides.
        total = sum(len(v) for v in captured[True].values())
        assert total == sum(len(s) for s in streams.values())

    @pytest.mark.parametrize("lockfree", [True, False])
    def test_shed_dispositions_deterministic_across_lanes(
        self, lockfree
    ):
        """Per-pod shedding against a standing backlog (unstarted
        pool, deterministic): both decode lanes record the same
        admitted/pod_budget/queue_full stream, and displaced
        earlier-admits land as payload-free second records."""
        recorder = self._recorder()
        pool, _index = make_pool(
            concurrency=1,
            max_queue_depth=6,
            pod_budget=2,
            lockfree_decode=lockfree,
        )
        pool.set_capture(recorder)

        def msg(pod, seq):
            return Message(
                topic=f"kv@{pod}@{MODEL}",
                payload=b"x",
                pod_identifier=pod,
                model_name=MODEL,
                seq=seq,
            )

        pool.add_tasks([msg("a", i + 1) for i in range(3)])
        pool.add_tasks([msg("b", i + 1) for i in range(3)])
        pool.add_tasks([msg("c", i + 1) for i in range(4)])
        stream = self._per_pod_stream(recorder)
        dispositions = {
            pod: [entry[4] for entry in entries]
            for pod, entries in stream.items()
        }
        # Deterministic regardless of the decode lane: pod a sheds
        # its own oldest at budget 2 (the victim's record carries the
        # shed reason at its own stream position — same-batch
        # displacement), and the stream matches the straight lane's.
        assert dispositions["a"].count("pod_budget") == 1
        assert dispositions["a"].count("admitted") == 2
        assert dispositions == self._expected_dispositions()
        displaced = [
            entry
            for entries in stream.values()
            for entry in entries
            if entry[4] != "admitted" and entry[3] is None
        ]
        assert displaced, "cross-batch displacement must be recorded"

    _EXPECTED_SHED = None

    @classmethod
    def _expected_dispositions(cls):
        """Compute the expected stream ONCE from the straight lane;
        both parametrized lanes must match it (and each other)."""
        if cls._EXPECTED_SHED is None:
            recorder = cls._recorder()
            pool, _index = make_pool(
                concurrency=1,
                max_queue_depth=6,
                pod_budget=2,
                lockfree_decode=False,
            )
            pool.set_capture(recorder)
            for pod, n in (("a", 3), ("b", 3), ("c", 4)):
                pool.add_tasks(
                    [
                        Message(
                            topic=f"kv@{pod}@{MODEL}",
                            payload=b"x",
                            pod_identifier=pod,
                            model_name=MODEL,
                            seq=i + 1,
                        )
                        for i in range(n)
                    ]
                )
            cls._EXPECTED_SHED = {
                pod: [entry[4] for entry in entries]
                for pod, entries in cls._per_pod_stream(
                    recorder
                ).items()
            }
        return cls._EXPECTED_SHED

    def test_resync_jobs_do_not_pollute_the_stream(self):
        """A mid-stream resync (purge + inventory re-apply) must
        leave the capture stream of live messages untouched and never
        appear in it — resync is synthesized repair, not ingress."""
        rng = random.Random(7)
        pods = [f"rs-{i}" for i in range(4)]
        streams = {
            pod: pod_stream(rng, pod, 20, token_offset=30000 * i)
            for i, pod in enumerate(pods)
        }

        def make_resync(pod):
            def build():
                done = threading.Event()
                job = ResyncJob(
                    pod_identifier=pod,
                    model_name=MODEL,
                    events=[],
                    on_done=lambda *a: done.set(),
                )
                return job, done

            return build

        captured = {}
        for with_resync in (False, True):
            recorder = self._recorder()
            pool, _index = make_pool(concurrency=2)
            pool.set_capture(recorder)
            run_storm(
                pool,
                None,
                streams,
                resync_for=(
                    {pods[0]: make_resync(pods[0])}
                    if with_resync
                    else None
                ),
                threads=2,
            )
            captured[with_resync] = self._per_pod_stream(recorder)
        assert captured[True] == captured[False]
        assert all(
            not topic.startswith("resync@")
            for entries in captured[True].values()
            for topic, *_rest in entries
        )

    def test_coalesced_capture_replays_to_same_state(self):
        """Coalesced vs uncoalesced publisher wire streams: each
        capture replays with zero divergence, and the replayed final
        states are identical — the coalescing parity contract
        extended through the capture/replay plane (fewer wire
        records, same truth)."""
        from llm_d_kv_cache_manager_tpu.obs.capture import (
            canonical_state,
        )
        from llm_d_kv_cache_manager_tpu.obs.replay import (
            load_capture,
            replay_capture,
        )

        publisher = TestPublisherCoalescing()
        plain_msgs, _t1, _e1 = publisher._publish_stream(
            coalesce_events=0, events_per_call=1, seed=13
        )
        co_msgs, _t2, _e2 = publisher._publish_stream(
            coalesce_events=8, events_per_call=1, seed=13
        )
        pod = plain_msgs[0].pod_identifier
        for message in co_msgs:
            message.pod_identifier = pod
            message.topic = plain_msgs[0].topic
        states = {}
        record_counts = {}
        for name, messages in (
            ("plain", plain_msgs),
            ("coalesced", co_msgs),
        ):
            recorder = self._recorder()
            pool, index = make_pool(concurrency=1)
            pool.set_capture(recorder)
            pool.start()
            pool.add_tasks(messages)
            pool.drain()
            pool.shutdown()
            blob = recorder.dump_bytes(index=index)
            art = load_capture(blob)
            record_counts[name] = len(art["records"])
            report = replay_capture(art, mode="single")
            assert report.ok, (name, report.to_dict())
            assert report.state_compared, (name, report.to_dict())
            states[name] = canonical_state(index)
        assert states["plain"] == states["coalesced"]
        assert record_counts["coalesced"] < record_counts["plain"]
