"""ZMQ transport integration: publisher -> subscriber -> pool -> index.

Follows the reference's integration strategy
(tests/integration/kv_events_test.go): subscriber lifecycle against
absent endpoints needs no publisher at all; the end-to-end flow runs over
loopback TCP in-process.
"""

import time

from llm_d_kv_cache_manager_tpu.kvcache.kvblock import (
    EMPTY_BLOCK_HASH,
    ChunkedTokenDatabase,
    TokenProcessorConfig,
)
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.in_memory import InMemoryIndex
from llm_d_kv_cache_manager_tpu.kvcache.kvblock.index import InMemoryIndexConfig
from llm_d_kv_cache_manager_tpu.kvevents.events import BlockStored
from llm_d_kv_cache_manager_tpu.kvevents.pool import Pool, PoolConfig
from llm_d_kv_cache_manager_tpu.kvevents.publisher import Publisher
from llm_d_kv_cache_manager_tpu.kvevents.subscriber_manager import (
    SubscriberManager,
)
from llm_d_kv_cache_manager_tpu.kvevents.zmq_subscriber import parse_topic

MODEL = "test-model"


def test_parse_topic():
    assert parse_topic("kv@pod-1@org/model") == ("pod-1", "org/model")
    assert parse_topic("kv@pod@m@lora") == ("pod", "m@lora")
    assert parse_topic("other@pod@m") is None
    assert parse_topic("kv@podonly") is None
    assert parse_topic("kv@@model") is None


def test_sequence_gap_counted_and_metered():
    """Lost publisher events surface in gap_count AND the Prometheus
    counter (kvtpu_kvevents_seq_gaps_total{pod=...}) so operators can
    alert on event loss (improves on the reference, which parses seq
    but ignores it — zmq_subscriber.go:143)."""
    import struct

    from llm_d_kv_cache_manager_tpu.kvevents.zmq_subscriber import (
        ZMQSubscriber,
        ZMQSubscriberConfig,
    )
    from llm_d_kv_cache_manager_tpu.metrics.collector import METRICS

    def metric_value():
        for metric in METRICS.kvevents_seq_gaps.collect():
            for sample in metric.samples:
                if (
                    sample.name.endswith("_total")
                    and sample.labels.get("pod") == "gap-pod"
                ):
                    return sample.value
        return 0.0

    sub = ZMQSubscriber(
        ZMQSubscriberConfig(
            pod_identifier="gap-pod", endpoint="tcp://127.0.0.1:1"
        ),
        sink=lambda message: None,
    )
    before = metric_value()

    def deliver(seq):
        return sub._parse_message(
            [b"kv@gap-pod@m", struct.pack(">Q", seq), b"payload"]
        )

    assert deliver(1) is not None
    assert deliver(2) is not None
    assert sub.gap_count == 0
    assert deliver(5) is not None  # 3 and 4 lost
    assert sub.gap_count == 2
    assert metric_value() - before == 2.0


class TestSubscriberManagerLifecycle:
    def test_lifecycle_without_publishers(self):
        manager = SubscriberManager(sink=lambda m: None)
        # Unroutable endpoints are fine: ZMQ connects lazily and retries.
        assert manager.ensure_subscriber("pod-a", "tcp://10.255.0.1:5557")
        assert not manager.ensure_subscriber("pod-a", "tcp://10.255.0.1:5557")
        # Endpoint change restarts.
        assert manager.ensure_subscriber("pod-a", "tcp://10.255.0.2:5557")
        assert manager.ensure_subscriber("pod-b", "tcp://10.255.0.3:5557")
        assert manager.active_pods() == ["pod-a", "pod-b"]
        assert manager.remove_subscriber("pod-a")
        assert not manager.remove_subscriber("pod-a")
        manager.shutdown()
        assert manager.active_pods() == []


def test_end_to_end_publish_subscribe_score():
    endpoint = "tcp://127.0.0.1:15782"
    index = InMemoryIndex(InMemoryIndexConfig(size=10_000))
    db = ChunkedTokenDatabase(TokenProcessorConfig(block_size=4))
    pool = Pool(index, db, PoolConfig(concurrency=2))
    pool.start()

    manager = SubscriberManager(sink=pool.add_task)
    manager.ensure_subscriber("pod-1", endpoint)

    publisher = Publisher(endpoint, "pod-1", MODEL, bind=True)
    try:
        # Let the SUB connection + subscription propagate, then publish
        # repeatedly until delivery is observed (PUB/SUB is lossy pre-join).
        tokens = [1, 2, 3, 4, 5, 6, 7, 8]
        expected = db.tokens_to_kv_block_keys(EMPTY_BLOCK_HASH, tokens, MODEL)
        deadline = time.monotonic() + 30
        found = {}
        while time.monotonic() < deadline and len(found) < 2:
            publisher.publish(
                BlockStored(
                    block_hashes=[0xA1, 0xA2],
                    parent_block_hash=None,
                    token_ids=tokens,
                    block_size=4,
                    medium="hbm",
                )
            )
            time.sleep(0.2)
            pool.drain()
            found = index.lookup(expected)
        assert set(found) == set(expected)
        assert found[expected[0]][0].pod_identifier == "pod-1"
        assert found[expected[0]][0].device_tier == "hbm"
    finally:
        publisher.close()
        manager.shutdown()
        pool.shutdown()
